"""Host-only profile of the raw (zero-decode) reader->loader path."""
import cProfile
import os
import pstats
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def main():
    tmpdir = tempfile.mkdtemp(prefix='profile_raw_')
    url = 'file://' + tmpdir + '/store'
    from bench_duty import build_raw_store
    build_raw_store(url, rows=512, image_size=160, num_classes=1000)

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(url, num_epochs=None, seed=7, shuffle_row_groups=True,
                         workers_count=1, reader_pool_type='thread')
    loader = JaxDataLoader(reader, batch_size=64, shuffling_queue_capacity=512, seed=7)
    it = iter(loader)
    for _ in range(4):
        next(it)  # warmup

    n_batches = 60
    prof = cProfile.Profile()
    t0 = time.perf_counter()
    prof.enable()
    for _ in range(n_batches):
        next(it)
    prof.disable()
    dt = time.perf_counter() - t0
    rows = n_batches * 64
    print('== {} rows in {:.3f}s = {:.0f} rows/s = {:.1f} us/row =='.format(
        rows, dt, rows / dt, 1e6 * dt / rows))
    stats = pstats.Stats(prof)
    stats.sort_stats('cumulative').print_stats(25)
    reader.stop()
    reader.join()


if __name__ == '__main__':
    main()
