"""Windowed telemetry history: the time dimension of the stall report.

Every counter the telemetry registry accumulates is cumulative-since-start, so
``stall_report`` over raw diagnostics answers "what dominated the whole run" —
useless for a controller (or an operator watching a live run) that needs to
know what dominates *right now*. This module adds the missing axis:

* :class:`HistoryRecorder` — a bounded time series of diagnostics snapshots,
  taken on a cadence (background thread) or on demand (``record_now``);
* **window deltas** — the diagnostics *difference* between two snapshots:
  counters subtract, gauges take their latest value, and derived rates
  (``rows_per_s``, a recomputed ``reader_wait_fraction``) are computed over
  the window's wall span, so :func:`windowed_stall_report` attributes the
  *last N seconds*, not the cumulative totals;
* **regression detection** — :func:`detect_regression` compares consecutive
  windows and names a throughput drop or stall rise between them;
* **persistence** — :meth:`HistoryRecorder.save`/:func:`load_history` write/
  read a JSONL file (one snapshot per line) that the offline autotune replay
  (``petastorm-tpu-autotune``) and ``petastorm-tpu-diagnose --watch`` both
  consume. The :class:`~petastorm_tpu.observability.exporters.JsonlExporter`
  format (``{"ts": ..., "metrics": {...}}``) is accepted too.

Readers with no loader attached have no ``reader_wait_s``; a window then
falls back to the pool-wait seconds as the wait signal and marks itself with
``wait_proxy='pool_wait'`` — the attribution stays honest about what it
measured. The recorder is cheap by construction: one ``diagnostics`` snapshot
per tick (dict merge + flatten, no per-row work), bounded deque storage, and
nothing at all when never started — ``autotune=False`` readers build no
recorder and pay zero.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from petastorm_tpu.observability import report as _report

#: default snapshot cadence; at one flatten/merge per second the recorder
#: stays far under the 1% overhead guard (tests/test_autotune.py)
DEFAULT_INTERVAL_S = 1.0

#: default snapshot retention (covers 10 min at the default cadence)
DEFAULT_CAPACITY = 600

#: diagnostics keys that are point-in-time readings, not monotonic
#: accumulators: a window takes their LATEST value instead of a delta
_GAUGE_SUFFIXES = ('_fraction', '_occupancy', '_depth', '_in_flight',
                   '_age_s', '_pinned', '_count_current')
_GAUGE_KEYS = frozenset({'workers_count'})


def _is_gauge_key(name):
    return name in _GAUGE_KEYS or name.endswith(_GAUGE_SUFFIXES)


def window_delta(older, newer):
    """The windowed diagnostics dict between two snapshots (each a
    ``{'ts': epoch_s, 'diag': {...}}`` mapping): counter keys subtract
    (clamped at 0 — a reset registry must not produce negative seconds),
    gauge keys carry the newer reading, and the derived keys below are added:

    * ``window_s`` — wall span of the window;
    * ``rows_per_s`` — ``rows_emitted`` delta over the span (None without a
      loader);
    * ``reader_wait_s``/``reader_wait_fraction`` — recomputed over the window
      (falling back to the pool-wait stage seconds when no loader wait is
      recorded, marked ``wait_proxy='pool_wait'``).
    """
    span_s = max(float(newer['ts']) - float(older['ts']), 1e-9)
    old_d, new_d = older['diag'], newer['diag']
    out = {}
    for name, value in new_d.items():
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        if _is_gauge_key(name):
            out[name] = value
        else:
            prev = old_d.get(name, 0)
            prev = prev if isinstance(prev, (int, float)) else 0
            out[name] = max(value - prev, 0)
    out['window_s'] = round(span_s, 4)
    rows = out.get('rows_emitted')
    out['rows_per_s'] = (round(rows / span_s, 2)
                         if isinstance(rows, (int, float)) and 'rows_emitted' in new_d
                         else None)
    wait = out.get('reader_wait_s', 0.0) or 0.0
    out['wait_proxy'] = None
    if wait <= 0.0 and 'reader_wait_s' not in new_d:
        # bare Reader (no loader): the consumer's blocked time is the
        # pool-wait stage, measured inside get_results
        wait = out.get('stage_pool_wait_s', 0.0) or 0.0
        out['reader_wait_s'] = round(wait, 4)
        out['wait_proxy'] = 'pool_wait'
    out['reader_wait_fraction'] = round(min(wait / span_s, 1.0), 4)
    return out


def windowed_stall_report(window):
    """:func:`petastorm_tpu.observability.stall_report` over a window delta —
    attribution of the window's wait, not the run's. The window's derived
    keys (``window_s``, ``rows_per_s``, ``wait_proxy``) are carried along."""
    rep = _report.stall_report(window)
    rep['window_s'] = window.get('window_s')
    rep['rows_per_s'] = window.get('rows_per_s')
    rep['wait_proxy'] = window.get('wait_proxy')
    return rep


def detect_regression(prev_window, cur_window, throughput_ratio=0.7,
                      stall_rise=0.15):
    """Compare two consecutive windows; return a regression record or None.

    * ``throughput_drop`` — the newer window's ``rows_per_s`` fell below
      ``throughput_ratio`` of the older one's;
    * ``stall_rise`` — the windowed ``reader_wait_fraction`` rose by more
      than ``stall_rise`` absolute.
    """
    if prev_window is None or cur_window is None:
        return None
    prev_rate, cur_rate = prev_window.get('rows_per_s'), cur_window.get('rows_per_s')
    if prev_rate and cur_rate is not None and cur_rate < throughput_ratio * prev_rate:
        return {'kind': 'throughput_drop', 'from_rows_per_s': prev_rate,
                'to_rows_per_s': cur_rate,
                'ratio': round(cur_rate / prev_rate, 4)}
    prev_wait = prev_window.get('reader_wait_fraction') or 0.0
    cur_wait = cur_window.get('reader_wait_fraction') or 0.0
    if cur_wait - prev_wait > stall_rise:
        return {'kind': 'stall_rise', 'from_fraction': prev_wait,
                'to_fraction': cur_wait}
    return None


class HistoryRecorder(object):
    """Bounded time series of diagnostics snapshots.

    :param diagnostics_fn: zero-arg callable returning the flat diagnostics
        mapping to record (``Reader.diagnostics`` / ``JaxDataLoader.diagnostics``
        / any dict source)
    :param interval_s: background cadence for :meth:`start`; :meth:`record_now`
        works without a thread
    :param capacity: snapshots retained (oldest rotate out)
    """

    def __init__(self, diagnostics_fn, interval_s=DEFAULT_INTERVAL_S,
                 capacity=DEFAULT_CAPACITY):
        if interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        if capacity < 2:
            raise ValueError('capacity must be >= 2 (a window needs two snapshots)')
        self._diagnostics_fn = diagnostics_fn
        self._interval_s = interval_s
        self._lock = threading.Lock()
        self._snapshots = deque(maxlen=capacity)
        self._stop_event = threading.Event()
        self._thread = None

    def __len__(self):
        with self._lock:
            return len(self._snapshots)

    @property
    def interval_s(self):
        return self._interval_s

    def record_now(self):
        """Take one snapshot immediately; returns it (``{'ts', 'diag'}``)."""
        try:
            diag = dict(self._diagnostics_fn())
        except Exception:  # noqa: BLE001 - a torn-down reader mid-shutdown must not kill the recorder thread
            return None
        snap = {'ts': time.time(), 'diag': diag}
        with self._lock:
            self._snapshots.append(snap)
        return snap

    def snapshots(self):
        with self._lock:
            return list(self._snapshots)

    # -- windows -------------------------------------------------------------

    def window(self, seconds=None):
        """Window delta between the newest snapshot and the oldest one within
        ``seconds`` of it (whole history when None). None with <2 snapshots."""
        with self._lock:
            snaps = list(self._snapshots)
        if len(snaps) < 2:
            return None
        newest = snaps[-1]
        older = snaps[0]
        if seconds is not None:
            horizon = newest['ts'] - seconds
            for snap in snaps[:-1]:
                if snap['ts'] >= horizon:
                    older = snap
                    break
            else:
                older = snaps[-2]
        return window_delta(older, newest)

    def window_last(self):
        """Delta between the two most recent snapshots — the controller's
        tick-to-tick evidence window."""
        with self._lock:
            if len(self._snapshots) < 2:
                return None
            older, newer = self._snapshots[-2], self._snapshots[-1]
        return window_delta(older, newer)

    def windowed_stall_report(self, seconds=None):
        win = self.window(seconds)
        return windowed_stall_report(win) if win is not None else None

    def regression(self, **kwargs):
        """Regression between the last two tick-to-tick windows, or None."""
        with self._lock:
            snaps = list(self._snapshots)[-3:]
        if len(snaps) < 3:
            return None
        return detect_regression(window_delta(snaps[0], snaps[1]),
                                 window_delta(snaps[1], snaps[2]), **kwargs)

    # -- background cadence --------------------------------------------------

    def start(self):
        if self._thread is not None:
            raise RuntimeError('HistoryRecorder already started')
        self._stop_event.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pstpu-history')
        self._thread.start()
        return self

    def _loop(self):
        self.record_now()
        while not self._stop_event.wait(self._interval_s):
            self.record_now()

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        return False

    # -- persistence ---------------------------------------------------------

    def save(self, path):
        """Write the retained snapshots as JSONL (one ``{'ts', 'diag'}`` per
        line) — the ``petastorm-tpu-autotune`` offline replay input. Returns
        the number of lines written."""
        snaps = self.snapshots()
        with open(path, 'w') as f:
            for snap in snaps:
                f.write(json.dumps(snap) + '\n')
        return len(snaps)


def load_history(path):
    """Read a history JSONL file into a snapshot list. Accepts both the
    :meth:`HistoryRecorder.save` format (``{'ts', 'diag'}``) and the
    :class:`~petastorm_tpu.observability.exporters.JsonlExporter` format
    (``{'ts', 'metrics'}``). Malformed lines are skipped."""
    snaps = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or 'ts' not in rec:
                continue
            diag = rec.get('diag', rec.get('metrics'))
            if isinstance(diag, dict):
                snaps.append({'ts': float(rec['ts']), 'diag': diag})
    return snaps


def history_windows(snapshots):
    """Consecutive tick-to-tick window deltas over a snapshot list (the
    offline replay's evidence stream)."""
    return [window_delta(a, b) for a, b in zip(snapshots, snapshots[1:])]
