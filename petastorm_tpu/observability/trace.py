"""Span tracing: a bounded ring buffer of Chrome-trace events.

Every instrumented pipeline stage (ventilator dispatch, chunk fetch, worker
read/decode, shuffle add/emit, loader collate, device staging) records one
*complete* event (``ph='X'``) when the process-wide level is ``'spans'``. The
ring is bounded (``deque(maxlen=...)``): a long run rotates oldest-first
instead of growing without bound, so tracing is safe to leave on.

Events are stored directly in the Chrome trace-event format (the dict Perfetto
and ``chrome://tracing`` load), so export is a ``json.dump`` — no conversion
pass over a large buffer:

    {"name": ..., "cat": ..., "ph": "X", "ts": <epoch µs>, "dur": <µs>,
     "pid": ..., "tid": ..., "args": {...}}

``ts`` is wall-clock epoch microseconds (``time.time()``) so spans recorded in
worker *processes* land on the same timeline as the main process; ``dur`` is
measured with ``perf_counter`` for precision. Worker-process events travel to
the main process piggybacked on the pool's results channel (drained
incrementally with :meth:`TraceRing.drain`), keyed by their own ``pid`` so
Perfetto renders one track per process.

Causal tracing (docs/observability.md "trace context"): every ventilated work
item is minted a :class:`TraceContext` — a trace id ``'<ns>:<seq>'`` (the
ventilator's 8-hex nonce plus the item's ventilation seq) and a parent span
id. The context is carried on a thread-local stack: spans opened while a
context is active stamp ``trace``/``span``/``parent`` into their event args
and push themselves as the parent of anything nested, so the ring holds a
reconstructable cross-process span TREE per batch, not a flat list. The trace
id itself doubles as the id of the (virtual) root node, so any process that
knows ``(ns, seq)`` — e.g. a serve client reading a ring frame header — can
derive the root with :func:`trace_root` and parent its own spans to it
without any extra bytes on the wire.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque, namedtuple

from petastorm_tpu.observability import metrics as _metrics

DEFAULT_TRACE_CAPACITY = 65536

#: causal identity of one ventilated item: ``trace`` is the stable per-item
#: trace id (``'<ns>:<seq>'``), ``span`` the id new spans should parent to.
#: A plain namedtuple: picklable (it rides the process pool's existing
#: ventilation tuples) and cheap enough to mint per row group.
TraceContext = namedtuple('TraceContext', ('trace', 'span'))


class TraceRing(object):
    """Thread-safe bounded event buffer. ``add`` is O(1); when full the oldest
    event is rotated out (``deque(maxlen)`` semantics)."""

    def __init__(self, capacity=DEFAULT_TRACE_CAPACITY):
        self._lock = threading.Lock()
        self._events = deque(maxlen=capacity)
        self._dropped = 0

    @property
    def capacity(self):
        return self._events.maxlen  # noqa: PT1301 - atomic attr fetch; maxlen is immutable on whichever deque is current

    def set_capacity(self, capacity):
        with self._lock:
            if capacity != self._events.maxlen:
                self._events = deque(self._events, maxlen=capacity)

    def __len__(self):
        return len(self._events)  # noqa: PT1301 - len(deque) is GIL-atomic; lock-free diagnostics read

    @property
    def dropped(self):
        """Events rotated out since creation (ring-full overwrites)."""
        return self._dropped

    def add(self, event):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)

    def extend(self, events):
        with self._lock:
            overflow = len(self._events) + len(events) - self._events.maxlen
            if overflow > 0:
                self._dropped += min(overflow, self._events.maxlen)
            self._events.extend(events)

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def drain(self):
        """Return and clear the buffered events (incremental shipping from
        worker processes to the main-process ring)."""
        with self._lock:
            events, self._events = list(self._events), deque(maxlen=self._events.maxlen)
            return events

    def clear(self):
        with self._lock:
            self._events.clear()


#: the per-process default ring
_ring = TraceRing()


def get_ring():
    return _ring


def record_span(name, cat, ts_epoch_s, dur_s, args=None):
    """Append one complete event to the process ring (caller has already
    checked the level)."""
    event = {'name': name, 'cat': cat, 'ph': 'X',
             'ts': int(ts_epoch_s * 1e6), 'dur': int(dur_s * 1e6),
             'pid': os.getpid(), 'tid': threading.get_ident()}
    if args:
        event['args'] = args
    _ring.add(event)


# -- trace-context propagation ------------------------------------------------

#: per-process monotonic span ids, mixed with the pid so ids stay unique
#: across the processes whose events merge into one ring (``next`` on
#: ``itertools.count`` is atomic under the GIL — no lock needed)
_span_ids = itertools.count(1)

_tls = threading.local()


def next_span_id():
    """A span id unique across every process contributing to a trace."""
    return '{:x}.{:x}'.format(os.getpid(), next(_span_ids))


def trace_root(ns, seq):
    """The deterministic virtual-root context of item ``seq`` minted under
    namespace ``ns``: the trace id doubles as the root span id, so any process
    knowing ``(ns, seq)`` can parent spans to the root with zero extra wire
    bytes (the serve client derives this from the ring frame header)."""
    trace_id = '{}:{}'.format(ns, seq)
    return TraceContext(trace_id, trace_id)


def root_of(ctx):
    """The virtual-root context of ``ctx``'s trace (None in, None out) —
    consumer-side spans (pool wait, collate, infeed) parent to the root, as
    siblings of the dispatch chain, not under some arbitrary worker span."""
    return None if ctx is None else TraceContext(ctx.trace, ctx.trace)


def current_trace():
    """The innermost active :class:`TraceContext` on this thread (or None)."""
    stack = getattr(_tls, 'stack', None)
    return stack[-1] if stack else None


def _push_trace(ctx):
    stack = getattr(_tls, 'stack', None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop_trace():
    stack = getattr(_tls, 'stack', None)
    if stack:
        stack.pop()


class _TraceScope(object):
    """Context manager installing one :class:`TraceContext` as this thread's
    active context (worker pools wrap ``worker.process`` in one so every stage
    inside lands in the item's span tree)."""

    __slots__ = ('_ctx',)

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        _push_trace(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc_value, tb):
        _pop_trace()
        return False


def use_trace(ctx):
    """Install a propagated :class:`TraceContext` around a block (no-op when
    ``ctx`` is None or the level is below ``'spans'``)."""
    if ctx is None or not _metrics.spans_on():
        return _NOOP_SPAN
    return _TraceScope(ctx)


def mint_trace(ns, seq):
    """Mint the trace for one ventilated item and install its root context
    (the ventilators call this around their dispatch block, so the ventilate
    span becomes the root's first child and ``pool.ventilate`` — which runs
    inside — captures the context for propagation)."""
    if not _metrics.spans_on():
        return _NOOP_SPAN
    return _TraceScope(trace_root(ns, seq))


class _Span(object):
    """Context manager recording one complete event on exit. Use only via
    :func:`span`/:func:`petastorm_tpu.observability.stage` so the off-level
    fast path stays a single int check.

    When a :class:`TraceContext` is active on the thread, the span stamps
    ``trace``/``span``/``parent`` into its event args and installs itself as
    the parent of anything opened inside it. :meth:`link` attaches the span to
    a context discovered only mid-flight (``pool_wait`` learns its item's
    identity from the frame it receives, after the span already opened)."""

    __slots__ = ('name', 'cat', 'args', '_t0', '_wall0', '_ctx', '_link',
                 '_sid', '_pushed')

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args
        self._link = None

    def __enter__(self):
        self._wall0 = time.time()
        ctx = current_trace()
        self._ctx = ctx
        if ctx is not None:
            self._sid = next_span_id()
            _push_trace(TraceContext(ctx.trace, self._sid))
            self._pushed = True
        else:
            self._sid = None
            self._pushed = False
        self._t0 = time.perf_counter()
        return self

    def link(self, ctx):
        """Adopt ``ctx`` as this span's parent context (overrides whatever was
        active at entry; None is ignored)."""
        if ctx is not None:
            self._link = ctx

    def __exit__(self, exc_type, exc_value, tb):
        dur = time.perf_counter() - self._t0
        if self._pushed:
            _pop_trace()
        record_span(self.name, self.cat, self._wall0, dur,
                    stamp_trace_args(self.args, self._link or self._ctx, self._sid))
        return False


def stamp_trace_args(args, ctx, sid=None):
    """Event args with the causal identity stamped in (``args`` unchanged when
    no context is active)."""
    if ctx is None:
        return args
    out = dict(args) if args else {}
    out['trace'] = ctx.trace
    out['span'] = sid if sid is not None else next_span_id()
    out['parent'] = ctx.span
    return out


class _NoopSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        return False

    def link(self, ctx):
        return None


_NOOP_SPAN = _NoopSpan()


def span(name, cat='pipeline', **args):
    """Trace-only span: records a Chrome-trace event at level ``'spans'``,
    no-op below. Must be used as a context manager (lint rule PT700)."""
    if not _metrics.spans_on():
        return _NOOP_SPAN
    return _Span(name, cat, args or None)


def instant(name, cat='pipeline', **args):
    """Zero-duration event (cache hit, rotation, …) at level ``'spans'``.
    Stamped into the active trace (as a leaf) when a context is installed."""
    if not _metrics.spans_on():
        return
    record_span(name, cat, time.time(), 0.0,
                stamp_trace_args(args or None, current_trace()))


def chrome_trace(events=None):
    """The Chrome trace-event JSON document (dict) for ``events`` (default:
    the process ring's current contents)."""
    if events is None:
        events = _ring.snapshot()
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def export_chrome_trace(path, events=None):
    """Write a Perfetto/chrome://tracing-loadable JSON file; returns the
    number of events written."""
    doc = chrome_trace(events)
    with open(path, 'w') as f:
        json.dump(doc, f)
    return len(doc['traceEvents'])
