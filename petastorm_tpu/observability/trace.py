"""Span tracing: a bounded ring buffer of Chrome-trace events.

Every instrumented pipeline stage (ventilator dispatch, chunk fetch, worker
read/decode, shuffle add/emit, loader collate, device staging) records one
*complete* event (``ph='X'``) when the process-wide level is ``'spans'``. The
ring is bounded (``deque(maxlen=...)``): a long run rotates oldest-first
instead of growing without bound, so tracing is safe to leave on.

Events are stored directly in the Chrome trace-event format (the dict Perfetto
and ``chrome://tracing`` load), so export is a ``json.dump`` — no conversion
pass over a large buffer:

    {"name": ..., "cat": ..., "ph": "X", "ts": <epoch µs>, "dur": <µs>,
     "pid": ..., "tid": ..., "args": {...}}

``ts`` is wall-clock epoch microseconds (``time.time()``) so spans recorded in
worker *processes* land on the same timeline as the main process; ``dur`` is
measured with ``perf_counter`` for precision. Worker-process events travel to
the main process piggybacked on the pool's results channel (drained
incrementally with :meth:`TraceRing.drain`), keyed by their own ``pid`` so
Perfetto renders one track per process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from petastorm_tpu.observability import metrics as _metrics

DEFAULT_TRACE_CAPACITY = 65536


class TraceRing(object):
    """Thread-safe bounded event buffer. ``add`` is O(1); when full the oldest
    event is rotated out (``deque(maxlen)`` semantics)."""

    def __init__(self, capacity=DEFAULT_TRACE_CAPACITY):
        self._lock = threading.Lock()
        self._events = deque(maxlen=capacity)
        self._dropped = 0

    @property
    def capacity(self):
        return self._events.maxlen

    def set_capacity(self, capacity):
        with self._lock:
            if capacity != self._events.maxlen:
                self._events = deque(self._events, maxlen=capacity)

    def __len__(self):
        return len(self._events)

    @property
    def dropped(self):
        """Events rotated out since creation (ring-full overwrites)."""
        return self._dropped

    def add(self, event):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)

    def extend(self, events):
        with self._lock:
            overflow = len(self._events) + len(events) - self._events.maxlen
            if overflow > 0:
                self._dropped += min(overflow, self._events.maxlen)
            self._events.extend(events)

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def drain(self):
        """Return and clear the buffered events (incremental shipping from
        worker processes to the main-process ring)."""
        with self._lock:
            events, self._events = list(self._events), deque(maxlen=self._events.maxlen)
            return events

    def clear(self):
        with self._lock:
            self._events.clear()


#: the per-process default ring
_ring = TraceRing()


def get_ring():
    return _ring


def record_span(name, cat, ts_epoch_s, dur_s, args=None):
    """Append one complete event to the process ring (caller has already
    checked the level)."""
    event = {'name': name, 'cat': cat, 'ph': 'X',
             'ts': int(ts_epoch_s * 1e6), 'dur': int(dur_s * 1e6),
             'pid': os.getpid(), 'tid': threading.get_ident()}
    if args:
        event['args'] = args
    _ring.add(event)


class _Span(object):
    """Context manager recording one complete event on exit. Use only via
    :func:`span`/:func:`petastorm_tpu.observability.stage` so the off-level
    fast path stays a single int check."""

    __slots__ = ('name', 'cat', 'args', '_t0', '_wall0')

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_value, tb):
        record_span(self.name, self.cat, self._wall0,
                    time.perf_counter() - self._t0, self.args)
        return False


class _NoopSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name, cat='pipeline', **args):
    """Trace-only span: records a Chrome-trace event at level ``'spans'``,
    no-op below. Must be used as a context manager (lint rule PT700)."""
    if not _metrics.spans_on():
        return _NOOP_SPAN
    return _Span(name, cat, args or None)


def instant(name, cat='pipeline', **args):
    """Zero-duration event (cache hit, rotation, …) at level ``'spans'``."""
    if not _metrics.spans_on():
        return
    record_span(name, cat, time.time(), 0.0, args or None)


def chrome_trace(events=None):
    """The Chrome trace-event JSON document (dict) for ``events`` (default:
    the process ring's current contents)."""
    if events is None:
        events = _ring.snapshot()
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def export_chrome_trace(path, events=None):
    """Write a Perfetto/chrome://tracing-loadable JSON file; returns the
    number of events written."""
    doc = chrome_trace(events)
    with open(path, 'w') as f:
        json.dump(doc, f)
    return len(doc['traceEvents'])
