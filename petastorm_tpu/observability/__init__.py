"""Pipeline telemetry: metrics registry, span tracing, stall attribution.

The measurement substrate every perf PR reports against (ROADMAP: the
BASELINE north-star is input-stall fraction). Three levels, selected with
``make_reader(telemetry=...)`` or :func:`configure`:

* ``'off'`` — every instrumentation helper returns after one int compare;
  no counters, no spans, no per-row work anywhere.
* ``'counters'`` (default) — named counters/gauges/histograms updated at
  block/batch granularity; the ``diagnostics`` surfaces become views over
  the registry; stall attribution works.
* ``'spans'`` (opt-in) — additionally records one Chrome-trace event per
  pipeline stage execution into a bounded ring, exportable with
  :func:`export_chrome_trace` and viewable in Perfetto.

The level and registries are **per-process** (worker processes receive the
config through the pool's setup args and ship snapshots/events back over the
results channel). Instrument with::

    from petastorm_tpu import observability as obs

    with obs.stage('decode', cat='worker'):       # timer + (at spans) an event
        ...
    obs.count('rows_decoded_total', n)            # block-granularity counter
    obs.gauge_set('shuffle_occupancy', size)

``stage``/``span`` must be closed on all paths — use them as context
managers; lint rule PT700 (``petastorm_tpu.analysis``) enforces this.

See ``docs/observability.md`` for the metric catalog and span taxonomy.
"""

from __future__ import annotations

import time as _time

from petastorm_tpu.observability import blackbox as _blackbox
from petastorm_tpu.observability import metrics as _metrics
from petastorm_tpu.observability import trace as _trace
from petastorm_tpu.observability.blackbox import (FlightRecorder,  # noqa: F401
                                                  format_postmortem, load_flight,
                                                  postmortem_report)
from petastorm_tpu.observability.critical_path import (critical_path,  # noqa: F401
                                                       critical_path_summary,
                                                       format_critical_path,
                                                       format_slowest_batches,
                                                       format_span_tree,
                                                       slowest_batches, span_tree,
                                                       stage_breakdown, traces_in)
from petastorm_tpu.observability.exporters import (JsonlExporter,  # noqa: F401
                                                   host_identity,
                                                   to_prometheus_text, write_prometheus)
from petastorm_tpu.observability.history import (HistoryRecorder,  # noqa: F401
                                                 detect_regression, history_windows,
                                                 load_history, window_delta,
                                                 windowed_stall_report)
from petastorm_tpu.observability.metrics import (counters_on, flatten_snapshot,  # noqa: F401
                                                 get_registry, merge_snapshots, spans_on)
from petastorm_tpu.observability.podagg import (format_pod_report,  # noqa: F401
                                                load_host_series, load_pod,
                                                pod_report)
from petastorm_tpu.observability.report import (decode_collate_share,  # noqa: F401
                                                format_stall_report, stall_report)
from petastorm_tpu.observability.trace import (TraceContext, chrome_trace,  # noqa: F401
                                               current_trace, export_chrome_trace,
                                               get_ring, instant, mint_trace,
                                               root_of, span, trace_root, use_trace)

_LEVELS = ('off', 'counters', 'spans')


class TelemetryConfig(object):
    """Picklable telemetry description, shipped into worker processes.

    :param level: 'off' | 'counters' | 'spans'
    :param trace_capacity: span ring size (events); oldest rotate out
    """

    def __init__(self, level='counters', trace_capacity=_trace.DEFAULT_TRACE_CAPACITY):
        if level not in _LEVELS:
            raise ValueError("telemetry level must be one of {}, got {!r}".format(
                _LEVELS, level))
        if trace_capacity < 1:
            raise ValueError('trace_capacity must be >= 1')
        self.level = level
        self.trace_capacity = trace_capacity

    def _key(self):
        return (self.level, self.trace_capacity)

    def __eq__(self, other):
        return type(self) is type(other) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return 'TelemetryConfig(level={!r}, trace_capacity={})'.format(
            self.level, self.trace_capacity)


def resolve_telemetry(telemetry):
    """Normalize the ``make_reader`` kwarg: ``None`` -> None (keep the current
    process configuration), a level string -> config, a config -> itself."""
    if telemetry is None:
        return None
    if isinstance(telemetry, TelemetryConfig):
        return telemetry
    if isinstance(telemetry, str):
        return TelemetryConfig(level=telemetry)
    raise ValueError("telemetry must be None, 'off'/'counters'/'spans', or a "
                     'TelemetryConfig, got {!r}'.format(telemetry))


def configure(telemetry):
    """Apply a telemetry config (or level string) to THIS process. ``None`` is
    a no-op. Returns the effective :class:`TelemetryConfig`."""
    config = resolve_telemetry(telemetry)
    if config is not None:
        _metrics.set_level(config.level)
        _trace.get_ring().set_capacity(config.trace_capacity)
    return current_config()


def current_config():
    """The process's effective config (what a Reader ships to its workers when
    no explicit ``telemetry=`` was given)."""
    return TelemetryConfig(level=_metrics.level_name(),
                           trace_capacity=_trace.get_ring().capacity)


# -- instrumentation helpers (each starts with the one-int-compare fast path) --

class _StageTimer(object):
    """Counter + (at spans level) trace event for one pipeline-stage
    execution. Accumulates into ``stage_<name>_s``.

    At spans level the timer participates in trace-context propagation
    exactly like :class:`petastorm_tpu.observability.trace._Span`: it stamps
    ``trace``/``span``/``parent`` from the thread's active
    :class:`TraceContext` and parents anything nested. :meth:`link` attaches
    the span to a context discovered only mid-flight (``pool_wait``)."""

    __slots__ = ('name', 'cat', 'args', '_t0', '_wall0', '_spans', '_ctx',
                 '_link', '_sid', '_pushed', '_act', '_act_prev')

    def __init__(self, name, cat, args, spans):
        self.name = name
        self.cat = cat
        self.args = args
        self._spans = spans
        self._link = None
        self._pushed = False

    def __enter__(self):
        # flight-recorder activity slot (docs/observability.md, "Flight
        # recorder"): one load + None compare when recording is off
        act = _blackbox._ACTIVITY
        self._act = act
        if act is not None:
            self._act_prev = act.enter(self.cat + '.' + self.name)
        if self._spans:
            self._wall0 = _time.time()
            ctx = _trace.current_trace()
            self._ctx = ctx
            if ctx is not None:
                self._sid = _trace.next_span_id()
                _trace._push_trace(_trace.TraceContext(ctx.trace, self._sid))
                self._pushed = True
            else:
                self._sid = None
        self._t0 = _time.perf_counter()
        return self

    def link(self, ctx):
        """Adopt ``ctx`` as this span's parent context (no-op below spans
        level or when ``ctx`` is None)."""
        if self._spans and ctx is not None:
            self._link = ctx

    def __exit__(self, exc_type, exc_value, tb):
        dur = _time.perf_counter() - self._t0
        _metrics.get_registry().stage_timer(self.name).record(dur)
        if self._act is not None:
            self._act.exit(self._act_prev)
        if self._spans:
            if self._pushed:
                _trace._pop_trace()
            _trace.record_span(
                self.name, self.cat, self._wall0, dur,
                _trace.stamp_trace_args(self.args, self._link or self._ctx,
                                        self._sid))
        return False


def stage(name, cat='pipeline', **args):
    """Time one execution of a named pipeline stage: accumulates the
    ``stage_<name>_s``/``stage_<name>_count`` counters and, at level
    ``'spans'``, records a Chrome-trace event. No-op at ``'off'``. Use as a
    context manager (PT700)."""
    if not _metrics.counters_on():
        return _trace._NOOP_SPAN
    return _StageTimer(name, cat, args or None, _metrics.spans_on())


def count(name, n=1):
    """Increment a counter (no-op at level 'off')."""
    if _metrics.counters_on():
        _metrics.get_registry().counter(name).inc(n)


def add_seconds(name, seconds):
    """Accumulate a float counter (no-op at level 'off')."""
    if _metrics.counters_on():
        _metrics.get_registry().counter(name).add(seconds)


def gauge_set(name, value):
    """Set a gauge (no-op at level 'off')."""
    if _metrics.counters_on():
        _metrics.get_registry().gauge(name).set(value)


def observe(name, value, buckets=_metrics.DEFAULT_BUCKETS):
    """Observe into a histogram (no-op at level 'off')."""
    if _metrics.counters_on():
        _metrics.get_registry().histogram(name, buckets).observe(value)


def snapshot():
    """This process's structured metrics snapshot (picklable)."""
    return _metrics.get_registry().snapshot()


def drain_trace_events():
    """Drain the process span ring (worker -> main shipping)."""
    return _trace.get_ring().drain()


def absorb_trace_events(events):
    """Merge span events shipped from another process into this ring."""
    if events:
        _trace.get_ring().extend(events)


__all__ = [
    'FlightRecorder', 'HistoryRecorder',
    'JsonlExporter', 'TelemetryConfig', 'TraceContext', 'absorb_trace_events',
    'add_seconds', 'chrome_trace', 'configure', 'count', 'counters_on',
    'critical_path', 'critical_path_summary', 'current_config', 'current_trace',
    'decode_collate_share', 'detect_regression', 'drain_trace_events',
    'export_chrome_trace', 'flatten_snapshot', 'format_critical_path',
    'format_pod_report', 'format_postmortem', 'format_slowest_batches',
    'format_span_tree', 'load_flight', 'postmortem_report',
    'format_stall_report', 'gauge_set', 'get_registry', 'get_ring',
    'history_windows', 'host_identity', 'instant', 'load_history',
    'load_host_series', 'load_pod', 'merge_snapshots', 'mint_trace', 'observe',
    'pod_report', 'resolve_telemetry', 'root_of', 'slowest_batches', 'snapshot',
    'span', 'span_tree', 'spans_on', 'stage', 'stage_breakdown', 'stall_report',
    'to_prometheus_text', 'trace_root', 'traces_in', 'use_trace',
    'window_delta', 'windowed_stall_report', 'write_prometheus',
]
