"""Pod-scale telemetry: merge per-host exports, find the straggler.

On a TPU pod every host runs the same input pipeline, and the SPMD step
blocks on the *slowest* one — a single starving host caps the whole pod's
duty cycle while every per-host dashboard looks "fine on average". This
module is the fleet view: it merges the host-stamped JSONL series that
:class:`~petastorm_tpu.observability.exporters.JsonlExporter` writes (one
file per host, each line carrying a :func:`host_identity` stamp), computes
per-host *windowed* throughput and stall attribution (reusing
``history.window_delta`` so counters delta correctly), measures the skew
across hosts, and names the straggler:

* **throughput straggler** — a host whose windowed ``rows_per_s`` fell below
  ``straggler_ratio`` (default 0.7) of the pod median;
* **stall straggler** — no throughput outlier, but a host whose windowed
  ``reader_wait_fraction`` exceeds the pod median by more than
  ``stall_margin`` absolute (default 0.15).

The straggler record carries the host's own stall-report bottleneck and hint,
so the callout is actionable ("host2 is starving: decode-bound, raise
workers_count") rather than just a name. Rendered by
``petastorm-tpu-diagnose --pod <dir>`` (add ``--watch`` to re-render live).
See docs/observability.md and docs/troubleshooting.md ("which host is
starving the pod?").
"""

from __future__ import annotations

import json
import os
from statistics import median

from petastorm_tpu.observability import history as _history
from petastorm_tpu.observability import report as _report

DEFAULT_STRAGGLER_RATIO = 0.7
DEFAULT_STALL_MARGIN = 0.15


def load_host_series(path):
    """Read one exporter JSONL file into a host series::

        {'host': <key>, 'identity': {...} | None, 'path': ...,
         'snapshots': [{'ts', 'diag'}, ...]}

    The host key comes from the newest line's identity stamp (exports written
    before host stamping existed fall back to the file's basename). A rotated
    backup (``path + '.1'``) is read first when present, so the series spans
    both generations. Malformed lines are skipped."""
    snapshots = []
    identity = None
    for source in (path + '.1', path):
        if not os.path.exists(source):
            continue
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or 'ts' not in rec:
                    continue
                diag = rec.get('diag', rec.get('metrics'))
                if not isinstance(diag, dict):
                    continue
                snapshots.append({'ts': float(rec['ts']), 'diag': diag})
                if isinstance(rec.get('host'), dict):
                    identity = rec['host']
    key = (identity or {}).get('host') or os.path.basename(path).rsplit('.', 1)[0]
    return {'host': key, 'identity': identity, 'path': path,
            'snapshots': snapshots}


def load_pod(source):
    """Load every host series of a pod. ``source`` is a directory (all
    ``*.jsonl`` files in it, sorted) or an iterable of file paths. Series
    sharing a host key are merged by snapshot time (a host that restarted
    into a new file stays one host)."""
    if isinstance(source, str):
        paths = sorted(os.path.join(source, name) for name in os.listdir(source)
                       if name.endswith('.jsonl'))
    else:
        paths = list(source)
    by_key = {}
    for path in paths:
        series = load_host_series(path)
        prev = by_key.get(series['host'])
        if prev is None:
            by_key[series['host']] = series
        else:
            prev['snapshots'].extend(series['snapshots'])
            prev['snapshots'].sort(key=lambda s: s['ts'])
            prev['identity'] = prev['identity'] or series['identity']
    return [by_key[k] for k in sorted(by_key)]


def host_window(series, seconds=None):
    """Windowed diagnostics for one host series: newest snapshot vs the oldest
    within ``seconds`` of it (whole series when None). None with <2
    snapshots."""
    snaps = series['snapshots']
    if len(snaps) < 2:
        return None
    newest = snaps[-1]
    older = snaps[0]
    if seconds is not None:
        horizon = newest['ts'] - seconds
        for snap in snaps[:-1]:
            if snap['ts'] >= horizon:
                older = snap
                break
        else:
            older = snaps[-2]
    return _history.window_delta(older, newest)


def pod_report(source, seconds=None, straggler_ratio=DEFAULT_STRAGGLER_RATIO,
               stall_margin=DEFAULT_STALL_MARGIN):
    """The pod-level stall report::

        {'hosts': [{'host', 'window_s', 'rows_per_s', 'reader_wait_fraction',
                    'bottleneck', 'hint', 'snapshots', 'identity'}, ...],
         'median_rows_per_s', 'throughput_skew', 'straggler': {...} | None}

    ``source`` is anything :func:`load_pod` accepts, or an already-loaded
    series list. ``throughput_skew`` is slowest/fastest windowed ``rows_per_s``
    (1.0 = perfectly even; None with <2 measurable hosts). The ``straggler``
    record names the host, the reason (``'throughput'`` or ``'stall'``), the
    measurement vs the pod median, and the host's own bottleneck attribution.
    """
    hosts = source if isinstance(source, list) else load_pod(source)
    rows = []
    for series in hosts:
        win = host_window(series, seconds)
        newest = series['snapshots'][-1]['diag'] if series['snapshots'] else {}
        entry = {'host': series['host'], 'identity': series['identity'],
                 'snapshots': len(series['snapshots']), 'window_s': None,
                 'rows_per_s': None, 'reader_wait_fraction': None,
                 'bottleneck': None, 'hint': None,
                 # elastic membership view (None = host not running elastic):
                 # a host stuck on an old generation after a reshard is the
                 # elastic analogue of a straggler (docs/parallelism.md)
                 'elastic_generation': newest.get('elastic_generation'),
                 'elastic_members': newest.get('elastic_member_count'),
                 # hang-watchdog evidence (observability/blackbox.py): a host
                 # with stall dumps is wedged, not merely slow — different
                 # remedy (post-mortem the flight files, not tune knobs)
                 'watchdog_stalls': int(newest.get('watchdog_stall_total', 0) or 0),
                 'watchdog_last_dump_ts': newest.get('watchdog_last_dump_ts')}
        if win is not None:
            rep = _report.stall_report(win)
            entry.update({'window_s': win.get('window_s'),
                          'rows_per_s': win.get('rows_per_s'),
                          'reader_wait_fraction': win.get('reader_wait_fraction'),
                          'bottleneck': rep.get('bottleneck'),
                          'hint': rep.get('hint')})
        rows.append(entry)
    rates = [r['rows_per_s'] for r in rows if r['rows_per_s']]
    med_rate = round(median(rates), 2) if rates else None
    skew = round(min(rates) / max(rates), 4) if len(rates) >= 2 and max(rates) else None
    generations = {r['host']: r['elastic_generation'] for r in rows
                   if r['elastic_generation'] is not None}
    elastic = None
    if generations:
        elastic = {'generations': generations,
                   'agreed': len(set(generations.values())) == 1}
    out = {'hosts': rows, 'median_rows_per_s': med_rate,
           'throughput_skew': skew, 'straggler': None, 'elastic': elastic}
    if med_rate:
        slow = [r for r in rows
                if r['rows_per_s'] is not None
                and r['rows_per_s'] < straggler_ratio * med_rate]
        if slow:
            worst = min(slow, key=lambda r: r['rows_per_s'])
            out['straggler'] = {'host': worst['host'], 'reason': 'throughput',
                                'rows_per_s': worst['rows_per_s'],
                                'pod_median_rows_per_s': med_rate,
                                'ratio': round(worst['rows_per_s'] / med_rate, 4),
                                'bottleneck': worst['bottleneck'],
                                'hint': worst['hint']}
            return out
    waits = [r['reader_wait_fraction'] for r in rows
             if r['reader_wait_fraction'] is not None]
    if len(waits) >= 2:
        med_wait = median(waits)
        stalled = [r for r in rows
                   if r['reader_wait_fraction'] is not None
                   and r['reader_wait_fraction'] - med_wait > stall_margin]
        if stalled:
            worst = max(stalled, key=lambda r: r['reader_wait_fraction'])
            out['straggler'] = {'host': worst['host'], 'reason': 'stall',
                                'reader_wait_fraction': worst['reader_wait_fraction'],
                                'pod_median_wait_fraction': round(med_wait, 4),
                                'bottleneck': worst['bottleneck'],
                                'hint': worst['hint']}
    return out


def format_pod_report(report):
    """Human-readable pod view (diagnose --pod)."""
    lines = ['pod: {} host(s), median {} rows/s, throughput skew {}'.format(
        len(report['hosts']),
        report['median_rows_per_s'] if report['median_rows_per_s'] is not None else '?',
        report['throughput_skew'] if report['throughput_skew'] is not None else '?')]
    show_elastic = bool(report.get('elastic'))
    lines.append('{:<16s} {:>12s} {:>8s} {:>7s}{}  {}'.format(
        'host', 'rows_per_s', 'wait', 'snaps',
        ' {:>9s}'.format('elastic') if show_elastic else '', 'bottleneck'))
    for r in report['hosts']:
        if show_elastic:
            gen = r.get('elastic_generation')
            cell = (' {:>9s}'.format('g{:.0f}/{:.0f}h'.format(
                gen, r.get('elastic_members') or 0))
                if gen is not None else ' {:>9s}'.format('-'))
        else:
            cell = ''
        lines.append('{:<16s} {:>12s} {:>8s} {:>7d}{}  {}'.format(
            r['host'],
            '{:.2f}'.format(r['rows_per_s']) if r['rows_per_s'] is not None else '-',
            '{:.1%}'.format(r['reader_wait_fraction'])
            if r['reader_wait_fraction'] is not None else '-',
            r['snapshots'], cell, r['bottleneck'] or '-'))
    if show_elastic and not report['elastic']['agreed']:
        lines.append('ELASTIC: hosts disagree on the shard-map generation {} — '
                     'a reshard is in progress, or a host cannot reach the '
                     'coordination directory'.format(
                         report['elastic']['generations']))
    wedged = [r for r in report['hosts'] if r.get('watchdog_stalls')]
    for r in wedged:
        lines.append('WATCHDOG {}: {} stall dump(s) recorded — the host stopped '
                     'making progress mid-stage; run `petastorm-tpu-blackbox` '
                     'on its flight directory for the wedged stacks'.format(
                         r['host'], r['watchdog_stalls']))
    s = report['straggler']
    if s is None:
        lines.append('no straggler: the pod is balanced within thresholds')
    elif s['reason'] == 'throughput':
        lines.append('STRAGGLER {}: {:.2f} rows/s vs pod median {:.2f} '
                     '({}x)'.format(s['host'], s['rows_per_s'],
                                    s['pod_median_rows_per_s'], s['ratio']))
        if s['hint']:
            lines.append('  its bottleneck: {} — {}'.format(s['bottleneck'], s['hint']))
    else:
        lines.append('STRAGGLER {}: input-wait {:.1%} vs pod median {:.1%}'.format(
            s['host'], s['reader_wait_fraction'], s['pod_median_wait_fraction']))
        if s['hint']:
            lines.append('  its bottleneck: {} — {}'.format(s['bottleneck'], s['hint']))
    return '\n'.join(lines)
