"""Low-overhead metrics registry: named counters, gauges and histograms.

The pipeline's observability substrate (see ``docs/observability.md``). Three
design constraints drive the shape:

* **Cheap when off, cheap when on.** The telemetry *level* is a module-level
  int read without any lock; every instrumentation helper checks it first and
  returns before touching the registry. Updates happen at block/batch
  granularity (a row group, a batch) — never per row — so even the
  ``'counters'`` default adds no per-row work to the hot loops.
* **Atomic in-process updates.** Each metric guards its state with its own
  tiny lock: worker threads, the ventilator thread and the consumer all update
  concurrently, and a torn float accumulation would silently skew the stall
  attribution the whole subsystem exists to make trustworthy.
* **Mergeable across processes.** :meth:`MetricsRegistry.snapshot` returns a
  picklable structured dict; :func:`merge_snapshots` sums counters/histograms
  (and gauges — per-worker occupancies add) so the pool workers' registries
  aggregate into one view. Process-pool workers ship their snapshots over the
  existing results channel (``workers/process_pool.py``), the same route the
  ``chunk_cache_*`` stats already travel.

The registry is per-process and shared by every reader in the process — the
diagnostics surface is a *view* over it, so two concurrent readers see merged
numbers (documented in ``docs/observability.md``).
"""

from __future__ import annotations

import threading

#: telemetry levels, ordered: each level includes the previous one's work
LEVEL_OFF, LEVEL_COUNTERS, LEVEL_SPANS = 0, 1, 2

_LEVEL_NAMES = {'off': LEVEL_OFF, 'counters': LEVEL_COUNTERS, 'spans': LEVEL_SPANS}

#: process-wide level; plain int read (no lock) on every hot-path check
_level = LEVEL_COUNTERS


def set_level(name):
    """Set the process-wide telemetry level ('off' | 'counters' | 'spans')."""
    global _level
    if name not in _LEVEL_NAMES:
        raise ValueError("telemetry level must be 'off', 'counters' or 'spans', "
                         'got {!r}'.format(name))
    _level = _LEVEL_NAMES[name]


def level_name():
    for name, value in _LEVEL_NAMES.items():
        if value == _level:
            return name
    return 'counters'


def counters_on():
    return _level >= LEVEL_COUNTERS


def spans_on():
    return _level >= LEVEL_SPANS


class Counter(object):
    """Monotonic accumulator (ints or seconds-as-float)."""

    kind = 'counter'
    __slots__ = ('_lock', '_value')

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    add = inc  # seconds-accumulator alias; same atomicity

    @property
    def value(self):
        return self._value


class Gauge(object):
    """Last-written value (occupancy, depth)."""

    kind = 'gauge'
    __slots__ = ('_lock', '_value')

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    @property
    def value(self):
        return self._value


#: default histogram bucket upper bounds, in seconds (latency-shaped)
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)


class Histogram(object):
    """Fixed-bucket histogram (cumulative-bucket Prometheus semantics)."""

    kind = 'histogram'
    __slots__ = ('_lock', '_bounds', '_counts', '_sum', '_count')

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self._bounds = tuple(sorted(buckets))
        self._counts = [0] * (len(self._bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        i = 0
        for bound in self._bounds:
            if v <= bound:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def state(self):
        with self._lock:
            return {'bounds': list(self._bounds), 'counts': list(self._counts),
                    'sum': self._sum, 'count': self._count}


class Timer(object):
    """Seconds-sum + call-count under ONE lock — the stage() hot path. In
    snapshots a timer flattens into the ``<name>_s`` / ``<name>_count``
    counter pair, so merge/flatten/Prometheus handling is unchanged."""

    kind = 'timer'
    __slots__ = ('_lock', '_sum', '_count')

    def __init__(self):
        self._lock = threading.Lock()
        self._sum = 0.0
        self._count = 0

    def record(self, seconds):
        with self._lock:
            self._sum += seconds
            self._count += 1

    @property
    def value(self):
        return self._sum


class MetricsRegistry(object):
    """Thread-safe name -> metric registry. Creation takes the registry lock
    once per metric name; subsequent lookups are a plain (GIL-safe) dict get."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        # stage name -> Timer: the per-call string concat + double lookup
        # measurably taxes small-row-group pipelines, so the hot stage() path
        # resolves its timer through this plain dict (benign race: concurrent
        # first calls both land on _get_or_create's locked creation)
        self._stage_timers = {}

    def _get_or_create(self, name, factory, kind):
        metric = self._metrics.get(name)  # noqa: PT1301 - intentional double-checked locking; dict.get is GIL-atomic and a miss re-checks under _lock
        if metric is None:
            with self._lock:
                metric = self._metrics.get(name)
                if metric is None:
                    metric = factory()
                    self._metrics[name] = metric
        if metric.kind != kind:
            raise TypeError('metric {!r} already registered as a {}, not a {}'.format(
                name, metric.kind, kind))
        return metric

    def counter(self, name):
        return self._get_or_create(name, Counter, 'counter')

    def gauge(self, name):
        return self._get_or_create(name, Gauge, 'gauge')

    def histogram(self, name, buckets=DEFAULT_BUCKETS):
        return self._get_or_create(name, lambda: Histogram(buckets), 'histogram')

    def stage_timer(self, name):
        """The :class:`Timer` behind ``stage_<name>_s``/``stage_<name>_count``,
        cached for the hot path."""
        timer = self._stage_timers.get(name)
        if timer is None:
            timer = self._get_or_create('stage_' + name, Timer, 'timer')
            self._stage_timers[name] = timer
        return timer

    def snapshot(self):
        """Picklable structured snapshot: ``{'counters': {name: value},
        'gauges': {...}, 'histograms': {name: state}}``."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, m in metrics.items():
            if m.kind == 'counter':
                out['counters'][name] = m.value
            elif m.kind == 'timer':
                with m._lock:
                    out['counters'][name + '_s'] = m._sum
                    out['counters'][name + '_count'] = m._count
            elif m.kind == 'gauge':
                out['gauges'][name] = m.value
            else:
                out['histograms'][name] = m.state()
        return out

    def reset(self):
        """Drop every metric (tests and fresh benchmark captures)."""
        with self._lock:
            self._metrics = {}
            self._stage_timers = {}


def merge_snapshots(snapshots):
    """Sum a list of :meth:`MetricsRegistry.snapshot` dicts into one: counters
    and histogram buckets add; gauges add too (per-worker occupancies are
    additive across a pool — the one cross-process gauge semantic we need)."""
    out = {'counters': {}, 'gauges': {}, 'histograms': {}}
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for name, v in snap.get('counters', {}).items():
            out['counters'][name] = out['counters'].get(name, 0) + v
        for name, v in snap.get('gauges', {}).items():
            out['gauges'][name] = out['gauges'].get(name, 0) + v
        for name, h in snap.get('histograms', {}).items():
            agg = out['histograms'].get(name)
            if agg is None or agg['bounds'] != h['bounds']:
                out['histograms'][name] = {'bounds': list(h['bounds']),
                                           'counts': list(h['counts']),
                                           'sum': h['sum'], 'count': h['count']}
            else:
                agg['counts'] = [a + b for a, b in zip(agg['counts'], h['counts'])]
                agg['sum'] += h['sum']
                agg['count'] += h['count']
    return out


def flatten_snapshot(snapshot):
    """Structured snapshot -> flat ``{name: number}`` dict for the diagnostics
    surface (histograms contribute ``<name>_count``/``<name>_sum``)."""
    flat = {}
    flat.update(snapshot.get('counters', {}))
    flat.update(snapshot.get('gauges', {}))
    for name, h in snapshot.get('histograms', {}).items():
        flat[name + '_count'] = h['count']
        flat[name + '_sum'] = h['sum']
    return flat


#: the per-process default registry
_registry = MetricsRegistry()


def get_registry():
    return _registry
