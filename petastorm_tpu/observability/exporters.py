"""Metric exporters: Prometheus text exposition and periodic JSONL flush.

Both consume :meth:`MetricsRegistry.snapshot` dicts, so they work equally on
the live process registry and on cross-process merges
(:func:`petastorm_tpu.observability.metrics.merge_snapshots`).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import socket
import sys
import threading
import time

from petastorm_tpu.observability import metrics as _metrics

_NAME_SANITIZE = re.compile(r'[^a-zA-Z0-9_:]')

#: when this process started exporting — lets the pod aggregator tell a
#: restarted host (fresh counters) from a stalled one (same counters)
_BOOT_TS = round(time.time(), 3)


def host_identity(key=None):
    """This process's identity stamp for exported telemetry records::

        {'host': <short key>, 'process_index': <int|None>,
         'hostname': ..., 'pid': ..., 'boot_ts': <epoch s>}

    ``process_index`` comes from an already-imported jax (``jax.process_index``
    identifies the host in a TPU pod); the check is on ``sys.modules`` so a
    CPU-only export never triggers the heavy import. ``key`` overrides the
    short host key (the pod aggregator's grouping label) — ``bench_pod`` uses
    that to stamp its simulated hosts distinctly within one process."""
    process_index = None
    jax = sys.modules.get('jax')
    if jax is not None:
        try:
            process_index = int(jax.process_index())
        except Exception:  # noqa: BLE001 - uninitialized backends must not break exporting
            process_index = None
    hostname = socket.gethostname()
    pid = os.getpid()
    if key is None:
        key = ('proc{}'.format(process_index) if process_index is not None
               else '{}:{}'.format(hostname, pid))
    return {'host': key, 'process_index': process_index, 'hostname': hostname,
            'pid': pid, 'boot_ts': _BOOT_TS}


def _prom_name(name, prefix):
    return prefix + _NAME_SANITIZE.sub('_', name)


def to_prometheus_text(snapshot=None, prefix='pstpu_'):
    """Render a snapshot in the Prometheus text exposition format (0.0.4).

    Counters keep their name (``pstpu_rows_decoded_total``), gauges likewise;
    histograms expand to cumulative ``_bucket{le=...}`` series plus ``_sum``
    and ``_count``, per the exposition contract.
    """
    if snapshot is None:
        snapshot = _metrics.get_registry().snapshot()
    lines = []
    for name in sorted(snapshot.get('counters', {})):
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} counter'.format(metric))
        lines.append('{} {}'.format(metric, snapshot['counters'][name]))
    for name in sorted(snapshot.get('gauges', {})):
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} gauge'.format(metric))
        lines.append('{} {}'.format(metric, snapshot['gauges'][name]))
    for name in sorted(snapshot.get('histograms', {})):
        h = snapshot['histograms'][name]
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} histogram'.format(metric))
        cumulative = 0
        for bound, count in zip(h['bounds'], h['counts']):
            cumulative += count
            lines.append('{}_bucket{{le="{}"}} {}'.format(metric, bound, cumulative))
        lines.append('{}_bucket{{le="+Inf"}} {}'.format(metric, h['count']))
        lines.append('{}_sum {}'.format(metric, h['sum']))
        lines.append('{}_count {}'.format(metric, h['count']))
    return '\n'.join(lines) + '\n'


def write_prometheus(path, snapshot=None, prefix='pstpu_'):
    """One-shot exposition dump (node-exporter textfile-collector style)."""
    with open(path, 'w') as f:
        f.write(to_prometheus_text(snapshot, prefix=prefix))


def _count_lines(path):
    """Lines in ``path`` (0 when absent/unreadable). Bounded work: only ever
    called on rotated exports, whose size is capped by ``max_bytes``."""
    try:
        with open(path, 'rb') as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


class JsonlExporter(object):
    """Background thread appending one JSON line per interval to ``path``:
    ``{"ts": <epoch s>, "host": {...}, "metrics": {<flat name: value>}}``.
    Deterministic release via :meth:`stop`/:meth:`close` (or the context
    manager); the final flush runs on stop so short-lived runs still record
    their last state. A started exporter also registers an atexit hook, so a
    process that exits without stopping it still flushes the tail interval
    (the window a post-mortem needs most).

    Every line carries this process's :func:`host_identity` stamp so exports
    from several hosts can be merged by the pod aggregator
    (``observability/podagg.py``); ``host_key`` overrides the short key.

    Output growth is bounded when ``max_bytes`` is set: once the file would
    exceed the cap it rotates to ``path + '.1'`` (one backup generation, so
    on-disk use stays under ~2x the cap), and lines discarded with an
    overwritten backup are counted into ``telemetry_export_dropped_total`` —
    a silent gap in a telemetry series should itself be visible in telemetry.
    """

    def __init__(self, path, interval_s=5.0, snapshot_fn=None, max_bytes=None,
                 host_key=None):
        if interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        if max_bytes is not None and max_bytes < 1:
            raise ValueError('max_bytes must be >= 1 (or None for unbounded)')
        self._path = path
        self._interval_s = interval_s
        self._snapshot_fn = snapshot_fn or (lambda: _metrics.get_registry().snapshot())
        self._max_bytes = max_bytes
        self._host = host_identity(host_key)
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self._stop_event = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('JsonlExporter already started')
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pstpu-metrics-jsonl')
        self._thread.start()
        # a process that exits without stop() (crash-adjacent teardown, a
        # script that just returns) would otherwise silently drop the tail
        # interval — exactly the window a post-mortem needs most
        atexit.register(self._atexit_flush)
        return self

    def _atexit_flush(self):
        """Final-window flush at interpreter exit for exporters never
        stopped explicitly. Routed through :meth:`stop` so the behavior is
        identical to a deliberate shutdown."""
        if self._thread is not None:
            try:
                self.stop()
            except Exception:  # noqa: BLE001 - interpreter teardown must never raise from an atexit hook
                pass

    def _maybe_rotate(self, pending_bytes):
        if (self._max_bytes is None or self._bytes == 0
                or self._bytes + pending_bytes <= self._max_bytes):
            return
        backup = self._path + '.1'
        dropped = _count_lines(backup)  # about to be overwritten
        if dropped and _metrics.counters_on():
            _metrics.get_registry().counter('telemetry_export_dropped_total').inc(dropped)
        try:
            os.replace(self._path, backup)
        except OSError:
            return  # keep appending to the old file rather than losing the flush
        self._bytes = 0

    def _flush(self):
        line = json.dumps({'ts': round(time.time(), 3), 'host': self._host,
                           'metrics': _metrics.flatten_snapshot(self._snapshot_fn())}) + '\n'
        self._maybe_rotate(len(line))
        with open(self._path, 'a') as f:
            f.write(line)
        self._bytes += len(line)

    def _loop(self):
        while not self._stop_event.wait(self._interval_s):
            self._flush()

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
            try:
                atexit.unregister(self._atexit_flush)
            except Exception:  # noqa: BLE001 - interpreter-shutdown race
                pass
        self._flush()

    #: deliberate alias: `close()` is the conventional name callers reach for
    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
