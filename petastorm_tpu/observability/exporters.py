"""Metric exporters: Prometheus text exposition and periodic JSONL flush.

Both consume :meth:`MetricsRegistry.snapshot` dicts, so they work equally on
the live process registry and on cross-process merges
(:func:`petastorm_tpu.observability.metrics.merge_snapshots`).
"""

from __future__ import annotations

import json
import re
import threading
import time

from petastorm_tpu.observability import metrics as _metrics

_NAME_SANITIZE = re.compile(r'[^a-zA-Z0-9_:]')


def _prom_name(name, prefix):
    return prefix + _NAME_SANITIZE.sub('_', name)


def to_prometheus_text(snapshot=None, prefix='pstpu_'):
    """Render a snapshot in the Prometheus text exposition format (0.0.4).

    Counters keep their name (``pstpu_rows_decoded_total``), gauges likewise;
    histograms expand to cumulative ``_bucket{le=...}`` series plus ``_sum``
    and ``_count``, per the exposition contract.
    """
    if snapshot is None:
        snapshot = _metrics.get_registry().snapshot()
    lines = []
    for name in sorted(snapshot.get('counters', {})):
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} counter'.format(metric))
        lines.append('{} {}'.format(metric, snapshot['counters'][name]))
    for name in sorted(snapshot.get('gauges', {})):
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} gauge'.format(metric))
        lines.append('{} {}'.format(metric, snapshot['gauges'][name]))
    for name in sorted(snapshot.get('histograms', {})):
        h = snapshot['histograms'][name]
        metric = _prom_name(name, prefix)
        lines.append('# TYPE {} histogram'.format(metric))
        cumulative = 0
        for bound, count in zip(h['bounds'], h['counts']):
            cumulative += count
            lines.append('{}_bucket{{le="{}"}} {}'.format(metric, bound, cumulative))
        lines.append('{}_bucket{{le="+Inf"}} {}'.format(metric, h['count']))
        lines.append('{}_sum {}'.format(metric, h['sum']))
        lines.append('{}_count {}'.format(metric, h['count']))
    return '\n'.join(lines) + '\n'


def write_prometheus(path, snapshot=None, prefix='pstpu_'):
    """One-shot exposition dump (node-exporter textfile-collector style)."""
    with open(path, 'w') as f:
        f.write(to_prometheus_text(snapshot, prefix=prefix))


class JsonlExporter(object):
    """Background thread appending one JSON line per interval to ``path``:
    ``{"ts": <epoch s>, "metrics": {<flat name: value>}}``. Deterministic
    release via :meth:`stop` (or the context manager); the final flush runs on
    stop so short-lived runs still record their last state."""

    def __init__(self, path, interval_s=5.0, snapshot_fn=None):
        if interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        self._path = path
        self._interval_s = interval_s
        self._snapshot_fn = snapshot_fn or (lambda: _metrics.get_registry().snapshot())
        self._stop_event = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('JsonlExporter already started')
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pstpu-metrics-jsonl')
        self._thread.start()
        return self

    def _flush(self):
        line = json.dumps({'ts': round(time.time(), 3),
                           'metrics': _metrics.flatten_snapshot(self._snapshot_fn())})
        with open(self._path, 'a') as f:
            f.write(line + '\n')

    def _loop(self):
        while not self._stop_event.wait(self._interval_s):
            self._flush()

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._flush()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
