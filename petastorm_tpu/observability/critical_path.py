"""Per-batch critical-path attribution over the causal span tree.

The trace ring (``observability/trace.py``) holds Chrome-trace events whose
``args`` carry ``trace``/``span``/``parent`` stamps: every ventilated item is
a trace, and the spans recorded across the ventilator thread, the worker
process, the consumer thread, the loader, and the infeed all parent into one
tree rooted at the item's *virtual root* (the trace id itself — see
``trace.trace_root``). This module reconstructs those trees and answers the
question the flat stall report cannot: **for THIS batch, which stage was on
the critical path** — fetch, decode, pool wait, ring wait, or collate?

Terminology:

* *makespan* — wall time from the earliest span start to the latest span end
  in the trace (dispatch → delivered), in µs.
* *self time* — a span's duration minus the parts covered by its children
  (clipped to the span's own interval), i.e. time attributable to the stage
  itself rather than to something it contains.
* *critical path* — the makespan decomposed along the timeline: at every
  instant the deepest active span owns the time, uncovered instants are
  ``'<untraced>'``, and the resulting ordered segments sum exactly to the
  makespan — the batch's dispatch-to-delivery latency, named stage by stage.

Events ship between processes on the pools' existing metrics piggyback, so a
main-process ring snapshot is normally enough; for a served reader, absorb the
daemon's events first (``ServedReader.service_trace_events()``).

Consumed by ``petastorm-tpu-diagnose --batch`` and the bench harness's
``critical_path`` summary block (tools/bench.py). See docs/observability.md.
"""

from __future__ import annotations

from petastorm_tpu.observability import trace as _trace

#: tree nodes are plain dicts so the structure round-trips through JSON
#: (bench summaries, diagnose output) without a conversion pass


def traces_in(events=None):
    """Group stamped events by trace id -> list of events (insertion order).
    Unstamped events (spans recorded with no active context) are skipped."""
    if events is None:
        events = _trace.get_ring().snapshot()
    out = {}
    for ev in events:
        args = ev.get('args') or {}
        tid = args.get('trace')
        if tid is not None:
            out.setdefault(tid, []).append(ev)
    return out


def span_tree(events, trace_id):
    """Reconstruct the span tree of one trace. Returns the virtual-root node
    (or None when the trace has no events)::

        {'span': <trace_id>, 'name': '<root>', 'trace': <trace_id>,
         'ts': µs, 'dur': µs (makespan), 'pid': None, 'children': [node, ...]}

    Child nodes carry the event fields (``name``/``cat``/``ts``/``dur``/
    ``pid``/``tid``/``args``) plus ``self_us`` and ``children``. Spans whose
    parent id never arrived (e.g. rotated out of the ring) attach to the root
    so no recorded work disappears from the view."""
    evs = traces_in(events).get(trace_id)
    if not evs:
        return None
    nodes = {}
    for ev in evs:
        args = ev.get('args') or {}
        sid = args.get('span')
        node = {'span': sid, 'parent': args.get('parent'), 'name': ev.get('name'),
                'cat': ev.get('cat'), 'ts': ev.get('ts', 0), 'dur': ev.get('dur', 0),
                'pid': ev.get('pid'), 'tid': ev.get('tid'),
                'args': {k: v for k, v in args.items()
                         if k not in ('trace', 'span', 'parent')},
                'children': []}
        if sid is not None:
            # duplicate span ids (retries replay the same item) keep the later
            # event — its timings supersede the abandoned attempt's
            nodes[sid] = node
    root = {'span': trace_id, 'parent': None, 'name': '<root>', 'cat': 'trace',
            'trace': trace_id, 'pid': None, 'tid': None, 'args': {},
            'children': []}
    for node in nodes.values():
        parent = nodes.get(node['parent']) if node['parent'] != trace_id else None
        if parent is None or parent is node:
            root['children'].append(node)
        else:
            parent['children'].append(node)
    starts = [n['ts'] for n in nodes.values()]
    ends = [n['ts'] + n['dur'] for n in nodes.values()]
    root['ts'] = min(starts)
    root['dur'] = max(ends) - root['ts']  # makespan
    _finalize(root)
    return root


def _finalize(node):
    """Sort children by start time and compute ``self_us`` bottom-up."""
    node['children'].sort(key=lambda n: n['ts'])
    covered = 0
    p_start, p_end = node['ts'], node['ts'] + node['dur']
    for child in node['children']:
        _finalize(child)
        # clip to the parent interval: cross-process clocks can skew a child
        # slightly outside, and attribution must never go negative
        covered += max(0, min(child['ts'] + child['dur'], p_end)
                       - max(child['ts'], p_start))
    node['self_us'] = max(0, node['dur'] - covered)


def critical_path(tree):
    """Timeline decomposition of the makespan: at every instant, the deepest
    active span in the tree owns the time (a parent's interval cedes to the
    child doing the actual work). Returns ordered, merged segments
    ``[{'name', 'cat', 'pid', 'dur_us'}, ...]`` whose durations sum exactly to
    the makespan — the batch's dispatch-to-delivery latency named stage by
    stage. Instants covered by no span (queueing between a worker finishing
    and the consumer picking the result up, scheduler delay, ring wait on an
    uninstrumented path) surface as ``'<untraced>'`` segments rather than
    vanishing.

    A plain longest-child descent would be wrong here: handoffs are async, so
    a child routinely outlives its parent (the worker span starts after the
    ``ventilate`` span that caused it already closed) — the sweep handles
    that naturally."""
    spans = []

    def walk(node, depth):
        for child in node['children']:
            spans.append((depth, child))
            walk(child, depth + 1)

    walk(tree, 1)
    if not spans:
        return []
    bounds = sorted({b for _, n in spans for b in (n['ts'], n['ts'] + n['dur'])})
    segments = []
    for lo, hi in zip(bounds, bounds[1:]):
        best = None
        for depth, n in spans:
            if n['ts'] <= lo and n['ts'] + n['dur'] >= hi:
                # deepest wins; among equals the later-started (the span
                # actually progressing the item at this point)
                if (best is None or depth > best[0]
                        or (depth == best[0] and n['ts'] > best[1]['ts'])):
                    best = (depth, n)
        if best is None:
            seg = {'name': '<untraced>', 'cat': 'trace', 'pid': None}
        else:
            n = best[1]
            seg = {'name': n['name'], 'cat': n['cat'], 'pid': n['pid']}
        if segments and segments[-1]['name'] == seg['name'] \
                and segments[-1]['pid'] == seg['pid']:
            segments[-1]['dur_us'] += hi - lo
        else:
            seg['dur_us'] = hi - lo
            segments.append(seg)
    return segments


def stage_breakdown(tree):
    """Self time per stage name across the whole tree (µs) — where the
    makespan actually went, nesting counted once."""
    out = {}
    stack = [tree]
    while stack:
        node = stack.pop()
        if node['name'] != '<root>':
            out[node['name']] = out.get(node['name'], 0) + node['self_us']
        stack.extend(node['children'])
    return out


def _tree_stats(tree):
    pids = set()
    spans = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        if node['name'] != '<root>':
            pids.add(node['pid'])
            spans += 1
        stack.extend(node['children'])
    return spans, pids


def slowest_batches(events=None, top=5):
    """Batches ranked by makespan, slowest first::

        [{'trace', 'makespan_us', 'spans', 'processes',
          'stages': {name: self_µs}, 'critical_path': [...]}]
    """
    if events is None:
        events = _trace.get_ring().snapshot()
    rows = []
    for tid in traces_in(events):
        tree = span_tree(events, tid)
        if tree is None:
            continue
        spans, pids = _tree_stats(tree)
        rows.append({'trace': tid, 'makespan_us': tree['dur'], 'spans': spans,
                     'processes': len(pids), 'stages': stage_breakdown(tree),
                     'critical_path': critical_path(tree)})
    rows.sort(key=lambda r: r['makespan_us'], reverse=True)
    return rows[:top]


def critical_path_summary(events=None, top=3):
    """The bench harness's ``critical_path`` JSON block: traced-batch count
    plus the ``top`` slowest batches with their stage breakdowns."""
    if events is None:
        events = _trace.get_ring().snapshot()
    grouped = traces_in(events)
    return {'traced_batches': len(grouped),
            'slowest': slowest_batches(events, top=top)}


def format_span_tree(tree, max_depth=None):
    """Indented text rendering of one batch's span tree."""
    lines = ['trace {}  makespan {:.3f} ms'.format(tree.get('trace', tree['span']),
                                                   tree['dur'] / 1000.0)]

    def walk(node, depth):
        if max_depth is not None and depth > max_depth:
            return
        lines.append('{}{:<24s} {:>10.3f} ms  self {:>8.3f} ms  [pid {} {}]'.format(
            '  ' * depth, node['name'], node['dur'] / 1000.0,
            node['self_us'] / 1000.0, node['pid'], node['cat']))
        for child in node['children']:
            walk(child, depth + 1)

    for child in tree['children']:
        walk(child, 1)
    return '\n'.join(lines)


def format_critical_path(path):
    """One-line rendering: ``ventilate 0.1ms -> read_io 12.4ms -> ...`` with
    the dominant stage called out."""
    if not path:
        return 'critical path: (no spans)'
    chain = ' -> '.join('{} {:.3f}ms'.format(s['name'], s['dur_us'] / 1000.0)
                        for s in path)
    worst = max(path, key=lambda s: s['dur_us'])
    return ('critical path: {}\n  dominant stage: {} ({:.3f} ms on the path, '
            'pid {})'.format(chain, worst['name'], worst['dur_us'] / 1000.0,
                             worst['pid']))


def format_slowest_batches(rows):
    """Tabular rendering of :func:`slowest_batches` (diagnose --batch slowest)."""
    if not rows:
        return 'no traced batches in the ring (is telemetry at spans level?)'
    lines = ['{:<22s} {:>12s} {:>6s} {:>5s}  {}'.format(
        'trace', 'makespan_ms', 'spans', 'procs', 'dominant stage')]
    for r in rows:
        worst = (max(r['critical_path'], key=lambda s: s['dur_us'])
                 if r['critical_path'] else None)
        dom = ('{} ({:.3f} ms)'.format(worst['name'], worst['dur_us'] / 1000.0)
               if worst else '-')
        lines.append('{:<22s} {:>12.3f} {:>6d} {:>5d}  {}'.format(
            r['trace'], r['makespan_us'] / 1000.0, r['spans'], r['processes'], dom))
    return '\n'.join(lines)
