"""Crash-persistent flight recorder + hang watchdog + post-mortem analyzer.

The telemetry stack (metrics, spans, stall attribution) is in-memory and
observable only from a *live* process: when a worker SIGSEGVs in a native
kernel, a serve daemon is OOM-killed, or an elastic host wedges, every
counter and span ring dies with it. This module is the black box that
survives:

* **Flight file** — a per-process, mmap-backed, fixed-size ring of
  sequence-stamped binary records (periodic counter/gauge snapshots,
  protocol/supervision events, watchdog stack dumps, the last stall
  report). mmap stores land in the kernel page cache, so the recorded
  bytes survive SIGKILL/SIGSEGV *by construction* — no flush path needs
  to run on the way down. The reader is torn-record-tolerant: each record
  carries its sequence number in both header and trailer, and the ring's
  ``oldest``/``write`` offsets are advanced so the readable window only
  ever covers whole records.
* **Crash-cause footer** — ``faulthandler`` is armed on a per-process
  ``.crash`` sidecar file (C-level all-thread stacks on
  SIGSEGV/SIGABRT/SIGBUS/SIGFPE/SIGILL, signals no Python handler can
  survive), Python marker handlers stamp catchable signals (SIGTERM)
  straight into the flight header via a preallocated ``pack_into`` (the
  async-signal-safety discipline lint rule PT704 enforces), and an
  ``atexit`` hook writes a clean-shutdown marker — so "crashed" vs
  "exited" vs "killed" is decidable from the file alone.
* **Hang watchdog** — the recorder's background thread doubles as a
  watchdog: when the process's current pipeline stage (the activity slot
  the stage timers maintain) has been open past a stall threshold with no
  progress on any registered progress source, it dumps all-thread Python
  stacks and registered-lock state into the flight file and counts
  ``watchdog_stall_total``.
* **Post-mortem** — :func:`postmortem_report` merges the flight files of
  every process in a run directory (dead or alive) and reconstructs the
  last N seconds: per-process status + crash signal, the stage each
  process died in, a windowed stall report, recent supervision events,
  and a named probable cause. CLI: ``petastorm-tpu-blackbox DIR`` (also
  ``petastorm-tpu-diagnose --postmortem DIR``).

Recording is on by default whenever telemetry is at ``counters`` level
(``PSTPU_FLIGHT=0`` disables; ``PSTPU_FLIGHT_DIR`` relocates the run
directory) and structurally free when off: every hook is one module
attribute load + ``None`` compare. See docs/observability.md ("Flight
recorder") and docs/troubleshooting.md for the 60-second post-mortem
walkthrough.
"""

from __future__ import annotations

import atexit
import errno
import faulthandler
import json
import mmap
import os
import re
import signal
import socket
import struct
import sys
import tempfile
import threading
import time
import traceback

from petastorm_tpu.observability import metrics as _metrics

# ---------------------------------------------------------------------------
# on-disk format
# ---------------------------------------------------------------------------

MAGIC = b'PSTPUFLT'
VERSION = 1

#: header page size; the ring region starts here
HEADER_SIZE = 4096

#: default ring capacity (bytes of record data, excluding the header page)
DEFAULT_CAPACITY = 256 * 1024

#: record kinds
K_SNAPSHOT = 1   #: periodic flattened counter/gauge snapshot
K_EVENT = 2      #: protocol / supervision event
K_SPAN = 3       #: recent span events (spans level only)
K_STALL = 4      #: a stall report (recorded by the loader on close)
K_WATCHDOG = 5   #: watchdog stack + lock-state dump
K_MARK = 6       #: lifecycle mark (enabled, closing, ...)

KIND_NAMES = {K_SNAPSHOT: 'snapshot', K_EVENT: 'event', K_SPAN: 'span',
              K_STALL: 'stall', K_WATCHDOG: 'watchdog', K_MARK: 'mark'}

# fixed header prefix: magic, version, pid, capacity, start_ts, then the
# mutable fields patched in place at their own offsets below
_HDR = struct.Struct('<8sIIQd')          # 0..32
_OFF_WRITE = 32                          # u64 monotonic write offset
_OFF_SEQ = 40                            # u64 next record sequence
_OFF_OLDEST = 48                         # u64 oldest intact record offset
_OFF_CLEAN = 56                          # u32 clean-shutdown marker
_OFF_CRASH = 60                          # i32 signal + f64 ts (see _FOOTER)
_OFF_LABEL = 72                          # 32s component label
_OFF_HOSTNAME = 104                      # 64s hostname
_OFF_ACTIVITY = 168                      # f64 ts + 128s current stage name

_U64 = struct.Struct('<Q')
_U32 = struct.Struct('<I')
#: crash footer — preallocated so the signal-marker path never allocates a
#: Struct (async-signal-safety: PT704)
_FOOTER = struct.Struct('<id')
_ACT = struct.Struct('<d128s')

#: per-record framing: u32 payload len, u64 seq, u8 kind, f64 wall ts ...
#: payload ... u64 seq trailer. A record is valid iff both seqs agree.
_REC = struct.Struct('<IQBd')
_REC_TRAILER = struct.Struct('<Q')
_REC_OVERHEAD = _REC.size + _REC_TRAILER.size  # 29 bytes

_LABEL_SANITIZE = re.compile(r'[^A-Za-z0-9_.-]+')

#: flight files older than this whose owner pid is gone are swept at enable
_STALE_SWEEP_AGE_S = 6 * 3600.0


class FlightFileError(Exception):
    """A flight file is missing, truncated, or not a flight file."""


def default_dir():
    """The default run directory (``PSTPU_FLIGHT_DIR`` overrides)."""
    return os.environ.get('PSTPU_FLIGHT_DIR') or os.path.join(
        tempfile.gettempdir(), 'pstpu_flight')


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except OSError as e:
        return e.errno == errno.EPERM
    return True


def _sweep_stale(run_dir):
    """Unlink flight files (and sidecars) whose owner pid is gone and whose
    mtime is old — the default dir is shared across runs and tmpfs never
    reclaims it on its own."""
    now = time.time()
    try:
        entries = os.listdir(run_dir)
    except OSError:
        return
    for name in entries:
        if not (name.startswith('flight-') and
                (name.endswith('.bin') or name.endswith('.crash'))):
            continue
        path = os.path.join(run_dir, name)
        try:
            if now - os.path.getmtime(path) < _STALE_SWEEP_AGE_S:
                continue
            pid_part = name.rsplit('-', 2)[-2] if name.endswith('.bin') \
                else name.rsplit('-', 2)[-2]
            pid = int(pid_part)
            if not _pid_alive(pid):
                os.unlink(path)
        except (OSError, ValueError, IndexError):
            continue


# ---------------------------------------------------------------------------
# the writer
# ---------------------------------------------------------------------------

class FlightRecorder(object):
    """Per-process mmap-backed flight recorder.

    One instance per process (module-level singleton via :func:`enable`);
    :meth:`record` is thread-safe. The background thread started by
    :meth:`start` is both the snapshot pump (one flattened metrics snapshot
    per ``snapshot_interval_s``) and the hang watchdog.
    """

    def __init__(self, path, capacity=DEFAULT_CAPACITY, label='',
                 snapshot_interval_s=1.0, stall_threshold_s=30.0):
        if capacity < 4096:
            raise ValueError('capacity must be >= 4096 bytes')
        self.path = path
        self.capacity = int(capacity)
        self.label = label
        self.snapshot_interval_s = float(snapshot_interval_s)
        self.stall_threshold_s = float(stall_threshold_s)
        self._lock = threading.Lock()
        self._closed = False
        self._dropped = 0
        # logical (monotonic) byte offsets into the ring; position on disk is
        # HEADER_SIZE + off % capacity
        self._write_off = 0
        self._seq = 0
        self._oldest_off = 0
        self._live = []  # [(start_off, size)] of records inside the window
        # activity slot mirror (the mmap holds the crash-persistent copy)
        self._activity = ''
        self._activity_ts = 0.0
        # watchdog state
        self._watches = {}
        self._watch_sig = None
        self._last_progress_t = time.monotonic()
        self._stall_dumped = False
        self._locks = {}
        # spans-level piggyback: wall ts (us) of the last span already copied
        self._last_span_ts = 0.0
        self._stop_event = threading.Event()
        self._thread = None
        self._crash_file = None  # faulthandler sidecar, kept open for life

        fd = os.open(path, os.O_CREAT | os.O_TRUNC | os.O_RDWR, 0o644)
        try:
            os.ftruncate(fd, HEADER_SIZE + self.capacity)
            self._mm = mmap.mmap(fd, HEADER_SIZE + self.capacity)
        finally:
            os.close(fd)
        _HDR.pack_into(self._mm, 0, MAGIC, VERSION, os.getpid(),
                       self.capacity, time.time())
        label_b = _LABEL_SANITIZE.sub('_', label).encode()[:31]
        self._mm[_OFF_LABEL:_OFF_LABEL + 32] = label_b.ljust(32, b'\x00')
        host_b = socket.gethostname().encode()[:63]
        self._mm[_OFF_HOSTNAME:_OFF_HOSTNAME + 64] = host_b.ljust(64, b'\x00')

    # -- ring writes ---------------------------------------------------------

    def _put(self, off, data):
        """Copy ``data`` into the ring at logical offset ``off`` (wrapping)."""
        i = off % self.capacity
        end = i + len(data)
        if end <= self.capacity:
            self._mm[HEADER_SIZE + i:HEADER_SIZE + end] = data
        else:
            first = self.capacity - i
            self._mm[HEADER_SIZE + i:HEADER_SIZE + self.capacity] = data[:first]
            self._mm[HEADER_SIZE:HEADER_SIZE + len(data) - first] = data[first:]

    def record(self, kind, payload):
        """Append one record (``payload`` is a JSON-serializable dict).
        Oversized payloads are dropped (counted in ``dropped``); a closed
        recorder is a no-op."""
        data = json.dumps(payload, separators=(',', ':'),
                          default=repr).encode('utf-8', 'replace')
        need = _REC_OVERHEAD + len(data)
        with self._lock:
            if self._closed:
                return False
            if need > self.capacity:
                self._dropped += 1
                return False
            start = self._write_off
            new_off = start + need
            # evict whole records the new write will overwrite, and advance
            # the oldest pointer BEFORE the bytes land: a crash mid-write then
            # leaves the readable [oldest, write) window fully intact
            floor = new_off - self.capacity
            while self._live and self._live[0][0] < floor:
                self._live.pop(0)
            self._oldest_off = self._live[0][0] if self._live else start
            _U64.pack_into(self._mm, _OFF_OLDEST, self._oldest_off)
            seq = self._seq
            buf = (_REC.pack(len(data), seq, kind, time.time()) + data +
                   _REC_TRAILER.pack(seq))
            self._put(start, buf)
            self._live.append((start, need))
            self._seq = seq + 1
            self._write_off = new_off
            _U64.pack_into(self._mm, _OFF_SEQ, self._seq)
            # write offset last: it is the reader's valid-end marker
            _U64.pack_into(self._mm, _OFF_WRITE, new_off)
        return True

    @property
    def dropped(self):
        return self._dropped

    # -- activity slot (the "dying stage" field) -----------------------------

    def set_activity(self, name):
        """Overwrite the fixed-size current-activity slot in place. Called on
        every stage enter/exit — a single ``pack_into`` under the GIL, no
        record traffic."""
        self._activity = name
        self._activity_ts = time.time()
        self._stall_dumped = False
        try:
            # deliberately lock-free: a fixed-offset pack_into is atomic
            # enough for a forensic field, and the stage-timer hot path must
            # not contend with record()
            _ACT.pack_into(self._mm, _OFF_ACTIVITY, self._activity_ts,  # noqa: PT1301 - fixed-slot overwrite; hot path stays lock-free
                           name.encode()[:128])
        except (ValueError, TypeError):
            pass

    # -- crash footer (async-signal-safe: see PT704) -------------------------

    def stamp_crash(self, signum):
        """Stamp the crash-cause footer. May run inside a signal handler:
        only preallocated ``pack_into`` stores into the existing mmap — no
        allocation, locks, logging, or imports on this path."""
        try:
            _FOOTER.pack_into(self._mm, _OFF_CRASH, signum, time.time())  # noqa: PT1301 - MUST be lock-free: runs inside a signal handler (PT704)
        except (ValueError, TypeError):
            pass

    def mark_clean_shutdown(self):
        try:
            _U32.pack_into(self._mm, _OFF_CLEAN, 1)  # noqa: PT1301 - fixed-slot flag; callers hold the close() lock or are single-threaded at exit
        except (ValueError, TypeError):
            pass

    # -- watchdog / snapshot pump --------------------------------------------

    def watch(self, name, fn):
        """Register a progress source (zero-arg callable returning a number or
        any comparable). A change in any source resets the stall timer."""
        with self._lock:
            self._watches[name] = fn

    def unwatch(self, name):
        with self._lock:
            self._watches.pop(name, None)

    def register_lock(self, name, lock):
        """Register a lock whose held-state the watchdog dump reports."""
        with self._lock:
            self._locks[name] = lock

    def unregister_lock(self, name):
        with self._lock:
            self._locks.pop(name, None)

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pstpu-blackbox')
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop_event.wait(self.snapshot_interval_s):
            try:
                self._pump_once()
            except Exception:  # noqa: BLE001 - the black box must never take the process down
                pass

    def _pump_once(self, now=None):
        """One pump tick: metrics snapshot, span piggyback, watchdog check.
        Split out (and ``now``-injectable) for tests."""
        now = time.monotonic() if now is None else now
        if _metrics.counters_on():
            flat = _metrics.flatten_snapshot(_metrics.get_registry().snapshot())
            self.record(K_SNAPSHOT, {'metrics': flat})
            if _metrics.spans_on():
                self._pump_spans()
        self._check_stall(now)

    def _pump_spans(self):
        """Copy trace-ring events newer than the last tick into the flight
        file (bounded tail) so a post-mortem can show a partial span tree."""
        from petastorm_tpu.observability import trace as _trace
        events = _trace.get_ring().snapshot()
        fresh = [e for e in events
                 if isinstance(e, dict) and e.get('ts', 0) > self._last_span_ts]
        if not fresh:
            return
        fresh = fresh[-50:]
        self._last_span_ts = max(e.get('ts', 0) for e in fresh)
        self.record(K_SPAN, {'events': fresh})

    def _progress_signature(self):
        with self._lock:
            watches = list(self._watches.items())
        sig = []
        for name, fn in watches:
            try:
                sig.append((name, fn()))
            except Exception:  # noqa: BLE001 - a torn-down source must not kill the watchdog
                sig.append((name, None))
        return tuple(sig)

    def _check_stall(self, now):
        sig = self._progress_signature()
        if sig != self._watch_sig:
            self._watch_sig = sig
            self._last_progress_t = now
            self._stall_dumped = False
        if not self._activity or self._stall_dumped:
            return
        stage_age = time.time() - self._activity_ts
        if (stage_age < self.stall_threshold_s or
                now - self._last_progress_t < self.stall_threshold_s):
            return
        self._stall_dumped = True
        self.record(K_WATCHDOG, self._stall_dump(stage_age))
        if _metrics.counters_on():
            reg = _metrics.get_registry()
            reg.counter('watchdog_stall_total').inc()
            reg.gauge('watchdog_last_dump_ts').set(round(time.time(), 3))

    def _stall_dump(self, stage_age):
        """All-thread Python stacks + registered-lock state + the wedged
        activity — the payload of a K_WATCHDOG record."""
        names = {t.ident: t.name for t in threading.enumerate()}
        threads = {}
        for ident, frame in sys._current_frames().items():
            key = '{} ({})'.format(names.get(ident, '?'), ident)
            threads[key] = ''.join(traceback.format_stack(frame))[-4000:]
        with self._lock:
            locks = {name: bool(lock.locked())
                     for name, lock in self._locks.items()
                     if hasattr(lock, 'locked')}
        return {'activity': self._activity,
                'age_s': round(stage_age, 3),
                'threads': threads,
                'locks': locks,
                'watch': dict(self._watch_sig or ())}

    # -- shutdown ------------------------------------------------------------

    def close(self, clean=True):
        """Stop the pump, write a final snapshot, stamp the clean-shutdown
        marker, and unmap. Idempotent."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if _metrics.counters_on():
            try:
                flat = _metrics.flatten_snapshot(_metrics.get_registry().snapshot())
                self.record(K_SNAPSHOT, {'metrics': flat})
            except Exception:  # noqa: BLE001 - best-effort final snapshot
                pass
        self.record(K_MARK, {'event': 'closing'})
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if clean:
                self.mark_clean_shutdown()
            try:
                self._mm.flush()
            except (OSError, ValueError):
                pass
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass


class _ActivitySlot(object):
    """The hook :class:`petastorm_tpu.observability._StageTimer` drives: one
    ``enter``/``exit`` pair per stage execution, maintaining the recorder's
    crash-persistent current-activity field."""

    __slots__ = ('_recorder', '_current')

    def __init__(self, recorder):
        self._recorder = recorder
        self._current = ''

    def enter(self, name):
        prev = self._current
        self._current = name
        self._recorder.set_activity(name)
        return prev

    def exit(self, prev):
        self._current = prev
        self._recorder.set_activity(prev)


# ---------------------------------------------------------------------------
# the process-wide singleton + hooks
# ---------------------------------------------------------------------------

#: the enabled recorder (None = off: every hook is one load + None compare)
_RECORDER = None
#: the stage-timer hook (non-None only while enabled)
_ACTIVITY = None
_ENABLE_COUNT = 0


def get_recorder():
    return _RECORDER


def enable(label='', run_dir=None, capacity=None, snapshot_interval_s=None,
           stall_threshold_s=None):
    """Create and arm this process's flight recorder (idempotent — returns
    the existing one when already enabled): mmap the flight file, start the
    snapshot/watchdog thread, arm faulthandler on the ``.crash`` sidecar,
    install signal markers and the atexit clean-shutdown hook."""
    global _RECORDER, _ACTIVITY, _ENABLE_COUNT
    if _RECORDER is not None:
        return _RECORDER
    run_dir = run_dir or default_dir()
    try:
        os.makedirs(run_dir, exist_ok=True)
    except OSError:
        return None
    _sweep_stale(run_dir)
    _ENABLE_COUNT += 1
    name = 'flight-{}-{}-{}.bin'.format(
        _LABEL_SANITIZE.sub('_', label or 'proc'), os.getpid(), _ENABLE_COUNT)
    path = os.path.join(run_dir, name)
    if capacity is None:
        capacity = int(os.environ.get('PSTPU_FLIGHT_CAPACITY', DEFAULT_CAPACITY))
    if snapshot_interval_s is None:
        snapshot_interval_s = float(os.environ.get('PSTPU_FLIGHT_INTERVAL', 1.0))
    if stall_threshold_s is None:
        stall_threshold_s = float(os.environ.get('PSTPU_FLIGHT_STALL_S', 30.0))
    try:
        rec = FlightRecorder(path, capacity=capacity, label=label,  # noqa: PT200 - process-lifetime singleton; released by disable()/atexit
                             snapshot_interval_s=snapshot_interval_s,
                             stall_threshold_s=stall_threshold_s)
    except OSError:
        return None
    _install_crash_capture(rec)
    atexit.register(_atexit_close)
    rec.record(K_MARK, {'event': 'enabled', 'label': label, 'pid': os.getpid(),
                        'argv': sys.argv[:3]})
    rec.start()
    _RECORDER = rec
    _ACTIVITY = _ActivitySlot(rec)
    return rec


def maybe_enable(label='', run_dir=None):
    """The wiring entry point pools/loaders/daemons call: enable recording
    unless ``PSTPU_FLIGHT=0`` or telemetry is off. Idempotent and cheap when
    already enabled (one global load)."""
    if _RECORDER is not None:
        return _RECORDER
    if os.environ.get('PSTPU_FLIGHT', '') == '0':
        return None
    if not _metrics.counters_on():
        return None
    return enable(label=label, run_dir=run_dir)


def disable():
    """Close the recorder and remove every hook (tests; long-lived hosts that
    want recording off after a phase)."""
    global _RECORDER, _ACTIVITY
    rec = _RECORDER
    _ACTIVITY = None
    _RECORDER = None
    if rec is not None:
        rec.close(clean=True)
        try:
            atexit.unregister(_atexit_close)
        except Exception:  # noqa: BLE001 - interpreter-shutdown race
            pass


def _atexit_close():
    rec = _RECORDER
    if rec is not None:
        rec.close(clean=True)


def record_event(payload):
    """Record a protocol/supervision event (no-op when disabled)."""
    rec = _RECORDER
    if rec is not None:
        rec.record(K_EVENT, payload)


def record_stall(report):
    """Record a stall report dict (the loader's closing report)."""
    rec = _RECORDER
    if rec is not None:
        rec.record(K_STALL, report)


def record_mark(payload):
    rec = _RECORDER
    if rec is not None:
        rec.record(K_MARK, payload)


def watch_progress(name, fn):
    """Register a watchdog progress source on the enabled recorder (no-op
    when disabled)."""
    rec = _RECORDER
    if rec is not None:
        rec.watch(name, fn)


def unwatch_progress(name):
    rec = _RECORDER
    if rec is not None:
        rec.unwatch(name)


def register_lock(name, lock):
    rec = _RECORDER
    if rec is not None:
        rec.register_lock(name, lock)


def unregister_lock(name):
    rec = _RECORDER
    if rec is not None:
        rec.unregister_lock(name)


#: signals a Python marker handler can observe on the way down. SIGSEGV-class
#: signals are faulthandler's job (no Python handler can run); SIGKILL is
#: unobservable and inferred post-mortem (no marker + no footer + dead pid).
_MARKER_SIGNALS = ('SIGTERM',)


def _signal_marker(signum, frame):
    """Stamp the crash footer, restore the default disposition and re-raise —
    the process still dies with the original signal. Async-signal-safe by
    construction (PT704): no allocation, locks, logging, or imports."""
    rec = _RECORDER
    if rec is not None:
        rec.stamp_crash(signum)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def _install_crash_capture(rec):
    """Arm faulthandler on the ``.crash`` sidecar and install Python marker
    handlers for catchable death signals whose disposition is still default
    (an application's own handler always wins)."""
    try:
        crash = open(rec.path + '.crash', 'w')
        faulthandler.enable(file=crash, all_threads=True)
        rec._crash_file = crash  # keep the fd alive for the process lifetime
    except (OSError, ValueError, RuntimeError):
        pass
    if threading.current_thread() is not threading.main_thread():
        return  # signal.signal is main-thread-only
    for name in _MARKER_SIGNALS:
        signum = getattr(signal, name, None)
        if signum is None:
            continue
        try:
            if signal.getsignal(signum) is signal.SIG_DFL:
                signal.signal(signum, _signal_marker)
        except (OSError, ValueError, RuntimeError):
            continue


# ---------------------------------------------------------------------------
# the torn-tolerant reader
# ---------------------------------------------------------------------------

def load_flight(path):
    """Parse one flight file into a dict (header fields + the intact record
    list). Torn/overwritten tail records are counted in ``torn``, never
    raised. Raises :class:`FlightFileError` only for a non-flight file."""
    with open(path, 'rb') as f:
        blob = f.read()
    if len(blob) < HEADER_SIZE:
        raise FlightFileError('{}: truncated header'.format(path))
    magic, version, pid, capacity, start_ts = _HDR.unpack_from(blob, 0)
    if magic != MAGIC:
        raise FlightFileError('{}: not a flight file'.format(path))
    if len(blob) < HEADER_SIZE + capacity:
        raise FlightFileError('{}: truncated ring'.format(path))
    write_off = _U64.unpack_from(blob, _OFF_WRITE)[0]
    oldest_off = _U64.unpack_from(blob, _OFF_OLDEST)[0]
    clean = _U32.unpack_from(blob, _OFF_CLEAN)[0]
    crash_signal, crash_ts = _FOOTER.unpack_from(blob, _OFF_CRASH)
    label = blob[_OFF_LABEL:_OFF_LABEL + 32].split(b'\x00', 1)[0].decode('utf-8', 'replace')
    hostname = blob[_OFF_HOSTNAME:_OFF_HOSTNAME + 64].split(b'\x00', 1)[0].decode('utf-8', 'replace')
    act_ts, act_raw = _ACT.unpack_from(blob, _OFF_ACTIVITY)
    activity = act_raw.split(b'\x00', 1)[0].decode('utf-8', 'replace')

    def get(off, n):
        i = off % capacity
        end = i + n
        if end <= capacity:
            return blob[HEADER_SIZE + i:HEADER_SIZE + end]
        return (blob[HEADER_SIZE + i:HEADER_SIZE + capacity] +
                blob[HEADER_SIZE:HEADER_SIZE + end - capacity])

    records, torn = [], 0
    off, prev_seq = oldest_off, None
    while off < write_off:
        if write_off - off < _REC_OVERHEAD:
            torn += 1
            break
        length, seq, kind, ts = _REC.unpack(get(off, _REC.size))
        total = _REC_OVERHEAD + length
        if length > capacity - _REC_OVERHEAD or off + total > write_off:
            torn += 1
            break
        trailer = _REC_TRAILER.unpack(get(off + _REC.size + length,
                                          _REC_TRAILER.size))[0]
        if trailer != seq or (prev_seq is not None and seq != prev_seq + 1):
            torn += 1
            break
        try:
            data = json.loads(get(off + _REC.size, length).decode('utf-8', 'replace'))
        except ValueError:
            data = None
        records.append({'seq': seq, 'kind': kind,
                        'kind_name': KIND_NAMES.get(kind, str(kind)),
                        'ts': ts, 'data': data})
        prev_seq = seq
        off += total
    return {'path': path, 'version': version, 'pid': pid, 'label': label,
            'hostname': hostname, 'capacity': capacity,
            'start_ts': start_ts, 'write_off': write_off,
            'clean_shutdown': bool(clean),
            'crash_signal': crash_signal or None,
            'crash_ts': crash_ts or None,
            'activity': activity, 'activity_ts': act_ts or None,
            'records': records, 'torn': torn}


def _signal_name(signum):
    try:
        return signal.Signals(signum).name
    except (ValueError, TypeError):
        return 'signal {}'.format(signum)


#: faulthandler banner -> signal name (the sidecar is the only witness for
#: signals no Python handler survives)
_SIDECAR_SIGNALS = (('Segmentation fault', 'SIGSEGV'), ('Aborted', 'SIGABRT'),
                    ('Bus error', 'SIGBUS'), ('Floating', 'SIGFPE'),
                    ('Illegal instruction', 'SIGILL'))


def parse_crash_sidecar(path):
    """Parse a faulthandler ``.crash`` sidecar: the fatal-signal name and the
    dumped stack text (None when absent/empty — the process did not die on a
    faulthandler-covered signal)."""
    try:
        with open(path, 'r', errors='replace') as f:
            text = f.read()
    except OSError:
        return None
    if not text.strip():
        return None
    sig = None
    for needle, name in _SIDECAR_SIGNALS:
        if needle in text:
            sig = name
            break
    return {'signal': sig, 'text': text[-8000:]}


# ---------------------------------------------------------------------------
# the post-mortem analyzer
# ---------------------------------------------------------------------------

def _process_status(flight, sidecar):
    """('exited'|'crashed'|'killed'|'running', signal_name|None)."""
    if flight['crash_signal']:
        return 'crashed', _signal_name(flight['crash_signal'])
    if sidecar is not None and sidecar.get('signal'):
        return 'crashed', sidecar['signal']
    if flight['clean_shutdown']:
        return 'exited', None
    if _pid_alive(flight['pid']):
        return 'running', None
    # no shutdown marker, no footer, no sidecar, pid gone: uncatchable death
    return 'killed', 'SIGKILL'


def _snapshot_window(records, last_s):
    """Windowed stall report over the K_SNAPSHOT records: newest snapshot vs
    the oldest one within ``last_s`` of it. None with fewer than 2."""
    snaps = [r for r in records
             if r['kind'] == K_SNAPSHOT and isinstance(r.get('data'), dict)
             and isinstance(r['data'].get('metrics'), dict)]
    if len(snaps) < 2:
        return None
    newest = snaps[-1]
    older = snaps[0]
    for r in snaps[:-1]:
        if r['ts'] >= newest['ts'] - last_s:
            older = r
            break
    if newest['ts'] <= older['ts']:
        older = snaps[-2]
    from petastorm_tpu.observability import history as _history
    window = _history.window_delta(
        {'ts': older['ts'], 'diag': older['data']['metrics']},
        {'ts': newest['ts'], 'diag': newest['data']['metrics']})
    return _history.windowed_stall_report(window)


def postmortem_report(run_dir, last_s=30.0):
    """Merge every flight file under ``run_dir`` and reconstruct the run's
    last seconds: per-process status/crash signal/dying stage, windowed
    stall report, last supervision events, watchdog dumps, and a named
    probable cause. Works from the files alone — every process may be dead."""
    paths = sorted(p for p in os.listdir(run_dir)
                   if p.startswith('flight-') and p.endswith('.bin'))
    procs, skipped = [], []
    for name in paths:
        path = os.path.join(run_dir, name)
        try:
            flight = load_flight(path)
        except (FlightFileError, OSError) as e:
            skipped.append({'path': path, 'error': str(e)})
            continue
        sidecar = parse_crash_sidecar(path + '.crash')
        status, sig = _process_status(flight, sidecar)
        records = flight['records']
        events = [r for r in records if r['kind'] == K_EVENT][-10:]
        watchdogs = [r for r in records if r['kind'] == K_WATCHDOG]
        stalls = [r for r in records if r['kind'] == K_STALL]
        spans = [r for r in records if r['kind'] == K_SPAN]
        span_events = [e for r in spans for e in (r['data'] or {}).get('events', [])]
        procs.append({
            'label': flight['label'], 'pid': flight['pid'],
            'hostname': flight['hostname'], 'path': path,
            'status': status, 'signal': sig,
            'activity': flight['activity'] or None,
            'activity_ts': flight['activity_ts'],
            'start_ts': flight['start_ts'],
            'torn_records': flight['torn'],
            'records_total': len(records),
            'last_event': events[-1]['data'] if events else None,
            'events': [r['data'] for r in events],
            'watchdog_dumps': len(watchdogs),
            'last_watchdog': watchdogs[-1]['data'] if watchdogs else None,
            'last_stall_report': stalls[-1]['data'] if stalls else None,
            'window_stall_report': _snapshot_window(records, last_s),
            'span_events': len(span_events),
            'span_tail': [e.get('name') for e in span_events[-8:]],
            'crash_stacks': (sidecar or {}).get('text'),
        })
    return {'run_dir': run_dir, 'last_s': last_s, 'processes': procs,
            'skipped': skipped, 'probable_cause': _probable_cause(procs)}


def _proc_desc(p):
    return '{} (pid {})'.format(p['label'] or 'proc', p['pid'])


def _probable_cause(procs):
    """Name the most likely reason the run ended, in evidence order: crash
    signal > uncatchable kill > watchdog-confirmed wedge > unclean exit."""
    if not procs:
        return None
    crashed = [p for p in procs if p['status'] == 'crashed']
    if crashed:
        p = crashed[0]
        where = ' mid `{}`'.format(p['activity']) if p['activity'] else ''
        return '{} died on {}{}'.format(_proc_desc(p), p['signal'], where)
    killed = [p for p in procs if p['status'] == 'killed']
    dead = killed
    wedged = [p for p in procs if p['watchdog_dumps']]
    if wedged:
        p = wedged[0]
        dump = p['last_watchdog'] or {}
        cause = '{} wedged in `{}` for {}s (watchdog stack dump recorded)'.format(
            _proc_desc(p), dump.get('activity') or p['activity'] or '?',
            dump.get('age_s', '?'))
        if dead:
            cause += '; peer {} is dead ({})'.format(
                _proc_desc(dead[0]), dead[0]['signal'] or 'no shutdown marker')
        return cause
    if killed:
        p = killed[0]
        where = ' mid `{}`'.format(p['activity']) if p['activity'] else ''
        return ('{} was killed (no shutdown marker, no crash footer — '
                'SIGKILL/OOM){}'.format(_proc_desc(p), where))
    unclean = [p for p in procs if p['status'] == 'running']
    if unclean:
        return '{} still running (or died without the pid being reaped)'.format(
            _proc_desc(unclean[0]))
    return 'no crash or stall evidence: every process exited cleanly'


def format_postmortem(report):
    """Human-readable rendering of :func:`postmortem_report`."""
    from petastorm_tpu.observability.report import format_stall_report
    lines = ['post-mortem of {} ({} flight file(s), last {:.0f}s window)'.format(
        report['run_dir'], len(report['processes']), report['last_s'])]
    if report['probable_cause']:
        lines.append('probable cause: {}'.format(report['probable_cause']))
    for p in report['processes']:
        head = '  {} [{}]'.format(_proc_desc(p), p['status'])
        if p['signal']:
            head += ' signal={}'.format(p['signal'])
        if p['activity']:
            head += ' last-stage={}'.format(p['activity'])
        lines.append(head)
        lines.append('    records={} torn={} watchdog_dumps={} span_events={}'.format(
            p['records_total'], p['torn_records'], p['watchdog_dumps'],
            p['span_events']))
        if p['last_event']:
            lines.append('    last event: {}'.format(
                json.dumps(p['last_event'], sort_keys=True)[:200]))
        if p['last_watchdog']:
            dump = p['last_watchdog']
            lines.append('    watchdog: wedged in `{}` for {}s; locks held: {}'.format(
                dump.get('activity'), dump.get('age_s'),
                [k for k, v in (dump.get('locks') or {}).items() if v] or 'none'))
        report_src = p['window_stall_report'] or p['last_stall_report']
        if report_src and 'reader_wait_s' in report_src:
            try:
                lines.append('    ' + format_stall_report(report_src)
                             .replace('\n', '\n    '))
            except (KeyError, TypeError):
                pass
    for s in report['skipped']:
        lines.append('  skipped {}: {}'.format(s['path'], s['error']))
    return '\n'.join(lines)


def main(argv=None):
    """``petastorm-tpu-blackbox DIR`` — one-command post-mortem forensics."""
    import argparse
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-blackbox',
        description='Merge the crash-persistent flight files under DIR and '
                    'reconstruct what the run was doing when it died or hung.')
    parser.add_argument('run_dir', nargs='?', default=None,
                        help='flight-file directory (default: the '
                             'PSTPU_FLIGHT_DIR / tmp default run dir)')
    parser.add_argument('--last', type=float, default=30.0, metavar='SECONDS',
                        help='stall-report window: attribute the last N '
                             'seconds before each process stopped recording')
    parser.add_argument('--json', action='store_true', dest='as_json')
    args = parser.parse_args(argv)
    run_dir = args.run_dir or default_dir()
    if not os.path.isdir(run_dir):
        print('no flight directory at {} (was recording enabled? '
              'PSTPU_FLIGHT_DIR relocates it)'.format(run_dir), file=sys.stderr)
        return 1
    report = postmortem_report(run_dir, last_s=args.last)
    if args.as_json:
        print(json.dumps(report, default=repr))
    else:
        print(format_postmortem(report))
    return 0


__all__ = ['DEFAULT_CAPACITY', 'FlightFileError', 'FlightRecorder',
           'K_EVENT', 'K_MARK', 'K_SNAPSHOT', 'K_SPAN', 'K_STALL',
           'K_WATCHDOG', 'default_dir', 'disable', 'enable', 'format_postmortem',
           'get_recorder', 'load_flight', 'main', 'maybe_enable',
           'parse_crash_sidecar', 'postmortem_report', 'record_event',
           'record_mark', 'record_stall', 'register_lock', 'unregister_lock',
           'unwatch_progress', 'watch_progress']


if __name__ == '__main__':
    sys.exit(main())
