"""Input-stall attribution: decompose the loader's ``reader_wait_s`` into
per-stage contributions and name the bottleneck.

The loader's ``reader_wait_s`` (time the consumer sat blocked in
``next(reader)``) is the online form of the BASELINE input-stall metric — but
a single number cannot say *why* the pipeline stalled. This module splits it
using the stage timers the telemetry layer accumulates:

* ``stage_pool_wait_s`` — measured **inside** ``pool.get_results`` (itself
  inside the reader-wait window): the share of the wait spent blocked on the
  worker pool's results transport.
* the remainder (``reader_wait_s - pool_wait``) is consumer-side assembly:
  row slicing / rebatching / ngram windowing in the results-queue reader.
* the pool-wait share is then attributed to the **worker** stages
  proportionally to their measured busy seconds (read IO, chunk fetch,
  decode, transform) — with the nested chunk-fetch seconds subtracted from
  the read timer so no second is counted twice. For thread/dummy pools these
  timers live in the same process's registry; for the process pool they
  arrive merged from the workers' own registries.

The result attributes ~100% of the measured wait to *named* stages (the
acceptance bar is >=90%), so "is it IO, decode, shuffle starvation, or device
staging?" has a mechanical answer. See ``docs/observability.md`` and the
"reading a stall report" section in ``docs/troubleshooting.md``.
"""

from __future__ import annotations

#: worker-side stage timers split proportionally under the pool wait, in
#: display order. 'read_io' is derived: stage_read_s minus the nested
#: stage_chunk_fetch_s. 'fused_decode' is the single-transition native
#: read→decode→collate pass (docs/native.md) — its seconds INCLUDE the page
#: faults of cold chunks, so on cold storage it partially overlaps what
#: read_io would have shown.
_WORKER_STAGES = ('read_io', 'chunk_fetch', 'fused_predicate', 'fused_decode',
                  'decode', 'transform')

#: stage -> one-line remedy, surfaced next to the named bottleneck
_HINTS = {
    'worker.read_io': 'storage-bound: enable chunk_cache for remote stores, or add IO parallelism (workers_count)',
    'worker.chunk_fetch': 'cold chunk mirror: warm the cache (epoch 2+ reads locally) or raise prefetch_budget',
    'worker.fused_predicate': 'fused predicate+decode dominates: tighten the predicate (page-stat skipping prunes more when clauses are selective) or add cores/workers (docs/native.md)',
    'worker.fused_decode': 'fused native decode dominates: add cores/workers — the pass is already one GIL-released call per batch (docs/native.md)',
    'worker.decode': 'decode-bound: more workers/cores, batched TransformSpec, image_decode_hints, or a RawTensorCodec store; check fused_fallback_reason:* counters for columns off the fused path',
    'worker.transform': 'transform-bound: vectorize with TransformSpec(batched=True)',
    'consumer.assembly': 'consumer-side slicing/rebatch: prefer output=columnar and larger batches',
    'pool.unattributed': 'workers idle or untimed: check ventilator starvation (items_in_flight) and results_queue_depth',
}


def stall_report(diagnostics):
    """Build the attribution dict from a diagnostics mapping (either
    ``JaxDataLoader.diagnostics`` or ``Reader.diagnostics`` merged with loader
    counters). Returns::

        {'reader_wait_s': ..., 'reader_wait_fraction': ...,
         'stages': {stage: seconds attributed},   # sums to ~reader_wait_s
         'attributed_s': ..., 'coverage': 0..1,
         'bottleneck': stage name or None, 'hint': str or None,
         'worker_busy_s': {stage: raw busy seconds}}
    """
    wait = float(diagnostics.get('reader_wait_s', 0.0) or 0.0)
    pool_wait = float(diagnostics.get('stage_pool_wait_s', 0.0) or 0.0)
    pool_wait = min(pool_wait, wait)
    assembly = max(wait - pool_wait, 0.0)

    read = float(diagnostics.get('stage_read_s', 0.0) or 0.0)
    chunk_fetch = float(diagnostics.get('stage_chunk_fetch_s', 0.0) or 0.0)
    busy = {
        'read_io': max(read - chunk_fetch, 0.0),
        'chunk_fetch': chunk_fetch,
        'fused_predicate': float(diagnostics.get('stage_fused_predicate_s', 0.0) or 0.0),
        'fused_decode': float(diagnostics.get('stage_fused_decode_s', 0.0) or 0.0),
        'decode': float(diagnostics.get('stage_decode_s', 0.0) or 0.0),
        'transform': float(diagnostics.get('stage_transform_s', 0.0) or 0.0),
    }
    total_busy = sum(busy.values())

    stages = {}
    if assembly > 0:
        stages['consumer.assembly'] = assembly
    if pool_wait > 0:
        if total_busy > 0:
            for name in _WORKER_STAGES:
                share = pool_wait * busy[name] / total_busy
                if share > 0:
                    stages['worker.' + name] = share
        else:
            # nothing timed on the worker side (telemetry off in workers, or
            # workers starved): name it rather than hide it
            stages['pool.unattributed'] = pool_wait

    attributed = sum(stages.values())
    coverage = (attributed / wait) if wait > 0 else 1.0
    bottleneck = max(stages, key=stages.get) if stages else None
    # supervision/recovery events (docs/robustness.md): restarts and requeues
    # cost wall time that shows up as pool wait, so a stall report that hides
    # them would misattribute recovery overhead to IO/decode
    recovery = {k: int(diagnostics.get(k, 0) or 0)
                for k in ('worker_restarts', 'items_requeued', 'items_quarantined')}
    # mixture accounting (docs/sequence.md): a starved mixture source skews
    # the sampled distribution long before it stalls the pipeline, so the
    # per-source counters ride along with the stall attribution
    mixture = {}
    i = 0
    while 'mixture_source_{}_rows'.format(i) in diagnostics:
        mixture[i] = {
            'rows': int(diagnostics['mixture_source_{}_rows'.format(i)] or 0),
            'tokens': int(diagnostics.get('mixture_source_{}_tokens'.format(i), 0) or 0),
            'exhausted': bool(diagnostics.get('mixture_source_{}_exhausted'.format(i), 0)),
        }
        i += 1
    # hang-watchdog evidence (observability/blackbox.py): a run that STOPPED
    # making progress looks identical to a slow one in the rate counters —
    # the watchdog's stall dumps are the discriminator, so they ride along
    watchdog = {'stalls': int(diagnostics.get('watchdog_stall_total', 0) or 0)}
    last_dump = diagnostics.get('watchdog_last_dump_ts')
    if last_dump:
        import time as _time
        watchdog['last_dump_age_s'] = round(max(_time.time() - float(last_dump), 0.0), 1)
    return {
        'reader_wait_s': round(wait, 4),
        'reader_wait_fraction': diagnostics.get('reader_wait_fraction'),
        'stages': {k: round(v, 4) for k, v in sorted(
            stages.items(), key=lambda kv: -kv[1])},
        'attributed_s': round(attributed, 4),
        'coverage': round(coverage, 4),
        'bottleneck': bottleneck,
        'hint': _HINTS.get(bottleneck),
        'worker_busy_s': {k: round(v, 4) for k, v in busy.items()},
        'recovery': recovery,
        'mixture': mixture,
        'watchdog': watchdog,
    }


def decode_collate_share(diagnostics):
    """The tentpole metric of the fused native path, machine-checkable from a
    diagnostics/flattened-snapshot mapping: Python decode + collate busy
    seconds as a fraction of pool wait (``None`` when nothing was timed).
    The fused pass itself is reported alongside (``fused_decode_share``) —
    it is GIL-released native work that replaces read+decode together, not a
    Python tail — so the pair shows WHERE the decode seconds went, not just
    that they left."""
    pool_wait = float(diagnostics.get('stage_pool_wait_s', 0.0) or 0.0)
    if pool_wait <= 0:
        return None
    tail = (float(diagnostics.get('stage_decode_s', 0.0) or 0.0) +
            float(diagnostics.get('stage_collate_s', 0.0) or 0.0))
    fused = float(diagnostics.get('stage_fused_decode_s', 0.0) or 0.0)
    return {'decode_collate_share': round(tail / pool_wait, 4),
            'fused_decode_share': round(fused / pool_wait, 4)}


def format_stall_report(report):
    """Human-readable rendering of :func:`stall_report`'s dict."""
    lines = ['stall report: reader_wait={:.3f}s'.format(report['reader_wait_s'])]
    frac = report.get('reader_wait_fraction')
    if frac is not None:
        lines[0] += ' ({:.1%} of loader wall time)'.format(frac)
    wait = report['reader_wait_s']
    for stage, seconds in report['stages'].items():
        pct = (seconds / wait * 100.0) if wait else 0.0
        lines.append('  {:<22s} {:>8.3f}s  {:5.1f}%'.format(stage, seconds, pct))
    lines.append('  attributed {:.1%} of the wait to named stages'.format(
        report['coverage']))
    if report['bottleneck'] is not None:
        lines.append('  bottleneck: {}'.format(report['bottleneck']))
        if report.get('hint'):
            lines.append('    hint: {}'.format(report['hint']))
    recovery = report.get('recovery') or {}
    if any(recovery.values()):
        lines.append('  recovery events: {} worker restart(s), {} item(s) requeued, '
                     '{} quarantined — see docs/robustness.md'.format(
                         recovery.get('worker_restarts', 0),
                         recovery.get('items_requeued', 0),
                         recovery.get('items_quarantined', 0)))
    mixture = report.get('mixture') or {}
    if mixture:
        lines.append('  mixture sources:')
        total_rows = sum(src['rows'] for src in mixture.values()) or 1
        for i, src in sorted(mixture.items()):
            lines.append('    source {:<3d} {:>10d} rows ({:5.1f}%)  {:>12d} tokens{}'.format(
                i, src['rows'], src['rows'] / total_rows * 100.0, src['tokens'],
                '  [exhausted]' if src['exhausted'] else ''))
    watchdog = report.get('watchdog') or {}
    if watchdog.get('stalls'):
        age = watchdog.get('last_dump_age_s')
        lines.append('  watchdog: {} stall dump(s) recorded{} — run '
                     '`petastorm-tpu-blackbox` on the flight directory for the '
                     'wedged stacks (docs/troubleshooting.md)'.format(
                         watchdog['stalls'],
                         ', last {}s ago'.format(age) if age is not None else ''))
    return '\n'.join(lines)
