"""``petastorm-tpu-diagnose``: one-shot pipeline health check for a dataset.

Runs a short measured read through the full loader pipeline with telemetry on
and prints the input-stall attribution report, the key pipeline counters, and
(optionally) a Chrome trace / Prometheus exposition dump::

    petastorm-tpu-diagnose file:///data/train --batches 50 \\
        --trace-out /tmp/pipeline.json --prom-out /tmp/metrics.prom

``--watch SECONDS`` switches to live mode: the read keeps running and the
stall report + fused-fallback table re-render every interval from **windowed
history** (``observability/history.py``) — each tick attributes the last
interval's wait, not the cumulative totals, and regressions between windows
are called out. ``--json`` stays machine-readable per tick (one JSON line
each), which also makes the output a replayable history for
``petastorm-tpu-autotune``.

``--batch TRACE_ID`` (or ``--batch slowest``) adds per-batch causal tracing
to the one-shot read: the slowest-batches table, the chosen batch's full
cross-process span tree, and its critical path
(``observability/critical_path.py``).

``--pod DIR`` renders the fleet instead of reading a dataset: DIR holds the
host-stamped JSONL exports of a pod's hosts (one
:class:`~petastorm_tpu.observability.exporters.JsonlExporter` file each), and
the pod report names per-host throughput/stall and the straggler host
(``observability/podagg.py``). Combine with ``--watch SECONDS`` to re-render
live as the hosts keep exporting.

``--postmortem [DIR]`` reconstructs a dead or hung run from the flight
recorder's crash-persistent files (``observability/blackbox.py``): per-process
crash cause, the stage each process died in, and the last window's stall
report — equivalent to the ``petastorm-tpu-blackbox`` console script.

``--fabric DIR`` renders the peer-to-peer chunk fabric instead: DIR is the
pod's coordination directory, and the report merges the per-process stats
snapshots the fabric clients flush under ``DIR/fabric/stats/`` into a
per-peer table — peer hits, fallbacks to the object store, the worst
observed breaker state, and mean fetch latency (``docs/fabric.md``).

Open traces in https://ui.perfetto.dev (or chrome://tracing). See
``docs/observability.md`` for how to read the output and
``docs/troubleshooting.md`` ("reading a stall report") for the remedies.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from petastorm_tpu import observability as obs


def diagnose(dataset_url, batch_size=64, batches=50, pool_type='thread',
             workers_count=3, telemetry='spans', use_batch_reader=False,
             reader_kwargs=None):
    """Read ``batches`` batches and return ``(stall_report_dict, diagnostics)``."""
    from petastorm_tpu.jax.loader import JaxDataLoader

    obs.configure(telemetry)
    if use_batch_reader:
        from petastorm_tpu.reader import make_batch_reader as factory
        extra = {}
    else:
        from petastorm_tpu.reader import make_reader as factory
        extra = {'output': 'columnar'}
    reader = factory(dataset_url, reader_pool_type=pool_type,
                     workers_count=workers_count, num_epochs=None,
                     telemetry=telemetry, **dict(extra, **(reader_kwargs or {})))
    # the loader context owns the reader: its exit stops and joins it
    with JaxDataLoader(reader, batch_size=batch_size, drop_last=False) as loader:
        it = iter(loader)
        for _ in range(batches):
            next(it)
        diag = loader.diagnostics
        return obs.stall_report(diag), diag


def fused_fallback_table(diagnostics):
    """``{column: {reason: count}}`` parsed from the labelled
    ``fused_fallback_column:<col>:<reason>`` counters — the per-column answer
    to "why is this column still on the Arrow path" (docs/native.md lists the
    reason catalog). Empty when every requested column fused (or the store
    predates the counters)."""
    table = {}
    for key, value in diagnostics.items():
        if not key.startswith('fused_fallback_column:'):
            continue
        try:
            _prefix, column, reason = key.split(':', 2)
        except ValueError:
            continue
        table.setdefault(column, {})[reason] = int(value)
    return table


#: fallback reason -> one-line remedy appended under the table when present
_FALLBACK_REMEDIES = {
    'predicate': 'predicate shape not natively evaluable — see docs/native.md '
                 'qualification matrix (in_lambda, string sets and partition-key '
                 'predicates stay on the Python path)',
    'compression': 'codec off the fused path (GZIP/BROTLI/LZO) — rewrite the '
                   'store with snappy/zstd/lz4 (materialize_dataset compression=)',
}


def format_fused_fallbacks(diagnostics):
    """Human-readable per-column fallback section (empty string when every
    column rode the fused/zero-copy native path)."""
    table = fused_fallback_table(diagnostics)
    if not table:
        return ''
    lines = ['fused-decode fallbacks (column -> reason x count; see '
             'docs/native.md for the reason catalog):']
    seen_reasons = set()
    for column in sorted(table):
        reasons = ', '.join('{} x{}'.format(r, c)
                            for r, c in sorted(table[column].items()))
        lines.append('  {:<24s} {}'.format(column, reasons))
        seen_reasons.update(table[column])
    for reason in sorted(seen_reasons & set(_FALLBACK_REMEDIES)):
        lines.append('  remedy[{}]: {}'.format(reason, _FALLBACK_REMEDIES[reason]))
    return '\n'.join(lines)


def serve_tenant_table(stats):
    """``{tenant_id: row}`` parsed from a serve daemon's stats document
    (``ReaderService.stats()`` / the control-plane ``stats`` op): per-tenant
    batches/bytes served, shared-decode hits, eviction flag, and the owning
    stream's fair-share occupancy (docs/serve.md)."""
    table = {}
    for stream_id, stream in (stats or {}).get('streams', {}).items():
        occupancy = stream.get('fair_share', {}).get('occupancy')
        for tenant_id, t in stream.get('tenants', {}).items():
            table[tenant_id] = {
                'stream': stream_id[:8],
                'dataset': stream.get('dataset_url'),
                'batches': t.get('batches_served', 0),
                'mbytes': round(t.get('bytes_served', 0) / 1e6, 1),
                'shared_hits': t.get('shared_decode_hits', 0),
                'weight': t.get('weight', 1),
                'occupancy': occupancy,
                'evicted': t.get('evicted', False),
            }
    return table


def format_serve_tenants(stats):
    """Human-readable per-tenant serving table (empty string when the daemon
    serves no tenants)."""
    table = serve_tenant_table(stats)
    if not table:
        return ''
    lines = ['serve tenants (batches / MB served, shared-decode hits, '
             'fair-share occupancy; docs/serve.md):',
             '  {:<8} {:<9} {:>8} {:>9} {:>12} {:>7} {:>10} {:>8}'.format(
                 'tenant', 'stream', 'batches', 'MB', 'shared_hits', 'weight',
                 'occupancy', 'evicted')]
    for tenant_id in sorted(table):
        row = table[tenant_id]
        lines.append('  {:<8} {:<9} {:>8} {:>9} {:>12} {:>7} {:>10} {:>8}'.format(
            tenant_id, row['stream'], row['batches'], row['mbytes'],
            row['shared_hits'], row['weight'],
            '-' if row['occupancy'] is None else row['occupancy'],
            'YES' if row['evicted'] else ''))
    lines.append('  evictions total: {}'.format((stats or {}).get('evictions', 0)))
    return '\n'.join(lines)


#: breaker-state severity for cross-observer merging: when two processes
#: disagree about a peer, report the least healthy view
_BREAKER_RANK = {'closed': 0, 'half-open': 1, 'open': 2}


def fabric_peer_table(coord_dir):
    """``{peer_host: row}`` merged from every fabric client's stats snapshot
    under ``<coord_dir>/fabric/stats/`` (one JSON file per process, flushed
    by :class:`~petastorm_tpu.fabric.client.FabricClient`): peer hits,
    failures, fallbacks, bytes copied, mean fetch latency, and the worst
    breaker state any observer reports (docs/fabric.md)."""
    stats_dir = os.path.join(coord_dir, 'fabric', 'stats')
    table = {}
    try:
        names = sorted(os.listdir(stats_dir))
    except OSError:
        return table
    for name in names:
        if not name.endswith('.json'):
            continue
        try:
            with open(os.path.join(stats_dir, name), 'r') as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue  # mid-replace or torn file: skip, the next flush heals it
        if not isinstance(snap, dict):
            continue
        breakers = snap.get('breakers') or {}
        for peer, stats in (snap.get('peers') or {}).items():
            row = table.setdefault(peer, {
                'hits': 0, 'failures': 0, 'fallbacks': 0, 'bytes': 0,
                'latency_sum': 0.0, 'latency_n': 0, 'breaker': 'closed'})
            for key in ('hits', 'failures', 'fallbacks', 'bytes'):
                row[key] += int(stats.get(key, 0))
            row['latency_sum'] += float(stats.get('latency_sum', 0.0))
            row['latency_n'] += int(stats.get('latency_n', 0))
            state = breakers.get(peer, 'closed')
            if _BREAKER_RANK.get(state, 0) > _BREAKER_RANK.get(row['breaker'], 0):
                row['breaker'] = state
    for row in table.values():
        row['mean_latency_ms'] = (
            round(1000.0 * row['latency_sum'] / row['latency_n'], 2)
            if row['latency_n'] else None)
    return table


def format_fabric_peers(table):
    """Human-readable per-peer fabric table (empty string when no fabric
    client has flushed stats yet)."""
    if not table:
        return ''
    lines = ['fabric peers (chunk copies served to this pod, fallbacks to '
             'the object store, breaker state; docs/fabric.md):',
             '  {:<20} {:>8} {:>9} {:>10} {:>10} {:>10} {:>12}'.format(
                 'peer', 'hits', 'failures', 'fallbacks', 'MB', 'breaker',
                 'latency_ms')]
    for peer in sorted(table):
        row = table[peer]
        lines.append('  {:<20} {:>8} {:>9} {:>10} {:>10} {:>10} {:>12}'.format(
            peer, row['hits'], row['failures'], row['fallbacks'],
            round(row['bytes'] / 1e6, 1), row['breaker'],
            '-' if row['mean_latency_ms'] is None else row['mean_latency_ms']))
    return '\n'.join(lines)


def diagnose_fabric(coord_dir, as_json=False, stream=None):
    """Merge the fabric stats snapshots under ``coord_dir`` and print the
    per-peer table. Returns 0, or 1 when no fabric stats exist."""
    stream = stream if stream is not None else sys.stdout
    table = fabric_peer_table(coord_dir)
    if as_json:
        print(json.dumps({'fabric_peers': table,
                          'host': obs.host_identity()}), file=stream)
        return 0 if table else 1
    if not table:
        print('no fabric stats under {} (no FabricClient has flushed yet — '
              'is the fabric enabled on this pod?)'.format(
                  os.path.join(coord_dir, 'fabric', 'stats')), file=stream)
        return 1
    print(format_fabric_peers(table), file=stream)
    return 0


def diagnose_serve(service_dir, as_json=False, stream=None):
    """Connect to the serve daemon under ``service_dir`` and print its
    per-tenant serving table + pool diagnostics. Returns 0, or 1 when no
    daemon is reachable."""
    stream = stream if stream is not None else sys.stdout
    from petastorm_tpu.serve.service import read_endpoint
    endpoint = read_endpoint(service_dir)
    if endpoint is None:
        print('no serve daemon endpoint under {} (is the daemon running?)'
              .format(service_dir), file=stream)
        return 1
    from multiprocessing.connection import Client
    try:
        conn = Client(endpoint['address'], family='AF_UNIX')
    except (OSError, ConnectionError) as e:
        print('serve daemon endpoint {} unreachable: {}'.format(
            endpoint['address'], e), file=stream)
        return 1
    try:
        conn.send({'op': 'stats'})
        reply = conn.recv()
    finally:
        conn.close()
    stats = reply.get('stats', {}) if reply.get('ok') else {}
    if as_json:
        print(json.dumps({'serve_stats': stats,
                          'tenants': serve_tenant_table(stats)}), file=stream)
        return 0
    table = format_serve_tenants(stats)
    print(table if table else 'serve daemon pid {} is up with no tenants'.format(
        stats.get('pid')), file=stream)
    pool = stats.get('pool', {})
    if pool:
        print('daemon pool:', file=stream)
        for key in sorted(pool):
            print('  {} = {}'.format(key, pool[key]), file=stream)
    return 0


def show_batch(batch_id='slowest', events=None, stream=None, top=10):
    """Render the slowest-batches table plus the selected batch's span tree
    and critical path from ``events`` (default: this process's trace ring).
    ``batch_id`` is a trace id (``'<ns>:<seq>'``) or ``'slowest'``. Returns 0,
    or 1 when no traced batches / no such trace exist."""
    stream = stream if stream is not None else sys.stdout
    if events is None:
        events = obs.get_ring().snapshot()
    rows = obs.slowest_batches(events, top=top)
    if not rows:
        print('no traced batches in the ring (tracing needs telemetry=spans)',
              file=stream)
        return 1
    print(obs.format_slowest_batches(rows), file=stream)
    trace_id = rows[0]['trace'] if batch_id in (None, 'slowest') else batch_id
    tree = obs.span_tree(events, trace_id)
    if tree is None:
        print('trace {} not found in the ring (rotated out, or never traced)'
              .format(trace_id), file=stream)
        return 1
    print(obs.format_span_tree(tree), file=stream)
    print(obs.format_critical_path(obs.critical_path(tree)), file=stream)
    return 0


def watch_pod(pod_dir, interval_s=2.0, ticks=None, window_s=None,
              as_json=False, stream=None):
    """Re-render the pod report from the exports under ``pod_dir`` every
    ``interval_s`` while the hosts keep appending. ``ticks`` bounds the run
    (None = until interrupted). Returns the number of ticks rendered."""
    stream = stream if stream is not None else sys.stdout
    rendered = 0
    try:
        while ticks is None or rendered < ticks:
            report = obs.pod_report(pod_dir, seconds=window_s)
            rendered += 1
            if as_json:
                print(json.dumps({'tick': rendered, 'ts': round(time.time(), 3),
                                  'pod': report}), file=stream, flush=True)
            else:
                print('--- pod tick {} ---'.format(rendered), file=stream)
                print(obs.format_pod_report(report), file=stream)
                stream.flush()
            if ticks is None or rendered < ticks:
                time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    return rendered


def watch(dataset_url, interval_s=2.0, ticks=None, batch_size=64,
          pool_type='thread', workers_count=3, telemetry='counters',
          use_batch_reader=False, reader_kwargs=None, as_json=False,
          stream=None):
    """Live mode: pump the loader on a background thread and re-render the
    WINDOWED stall report + fused-fallback table every ``interval_s``. Each
    tick covers only the last window (``observability/history.py``), so a
    bottleneck that appears mid-run shows up within one interval instead of
    being diluted by the cumulative totals. ``ticks`` bounds the run (None =
    until interrupted). Returns the number of ticks rendered."""
    from petastorm_tpu.jax.loader import JaxDataLoader
    from petastorm_tpu.observability import history as _history

    stream = stream if stream is not None else sys.stdout
    obs.configure(telemetry)
    if use_batch_reader:
        from petastorm_tpu.reader import make_batch_reader as factory
        extra = {}
    else:
        from petastorm_tpu.reader import make_reader as factory
        extra = {'output': 'columnar'}
    reader = factory(dataset_url, reader_pool_type=pool_type,
                     workers_count=workers_count, num_epochs=None,
                     telemetry=telemetry, **dict(extra, **(reader_kwargs or {})))
    stop = threading.Event()
    rendered = 0
    with JaxDataLoader(reader, batch_size=batch_size, drop_last=False) as loader:

        def pump():
            try:
                for _ in loader:
                    if stop.is_set():
                        return
            except Exception:  # noqa: BLE001 - shutdown race on stop(): the watch loop already ended
                pass

        pump_thread = threading.Thread(target=pump, daemon=True,
                                       name='pstpu-watch-pump')
        pump_thread.start()
        recorder = _history.HistoryRecorder(lambda: loader.diagnostics,
                                            interval_s=interval_s)
        recorder.record_now()
        try:
            while ticks is None or rendered < ticks:
                time.sleep(interval_s)
                recorder.record_now()
                window = recorder.window_last()
                if window is None:
                    continue
                rendered += 1
                report = _history.windowed_stall_report(window)
                regression = recorder.regression()
                fallbacks = fused_fallback_table(
                    {k: v for k, v in window.items()
                     if not (k.startswith('fused_fallback_column:') and not v)})
                if as_json:
                    print(json.dumps({'tick': rendered, 'ts': round(time.time(), 3),
                                      'host': obs.host_identity(),
                                      'window': report,
                                      'fused_fallbacks': fallbacks,
                                      'regression': regression}),
                          file=stream, flush=True)
                    continue
                print('--- watch tick {} (window {:.1f}s, {} rows/s) ---'.format(
                    rendered, window['window_s'],
                    window['rows_per_s'] if window['rows_per_s'] is not None else '?'),
                    file=stream)
                print(obs.format_stall_report(report), file=stream)
                if fallbacks:
                    lines = ['fused-decode fallbacks this window:']
                    for column in sorted(fallbacks):
                        lines.append('  {:<24s} {}'.format(column, ', '.join(
                            '{} x{}'.format(r, c)
                            for r, c in sorted(fallbacks[column].items()))))
                    print('\n'.join(lines), file=stream)
                if regression is not None:
                    print('  REGRESSION between windows: {}'.format(regression),
                          file=stream)
                stream.flush()
        except KeyboardInterrupt:
            pass
        finally:
            stop.set()
    # the loader context has stopped the reader: the pump's next() unblocks
    # with StopIteration; join it so no thread outlives this call mid-teardown
    pump_thread.join(timeout=10)
    return rendered


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-diagnose',
        description='Measure a short read of the dataset and attribute input '
                    'stalls to pipeline stages.')
    parser.add_argument('dataset_url', nargs='?', default=None)
    parser.add_argument('--serve', metavar='SERVICE_DIR', default=None,
                        help='instead of reading a dataset, connect to the '
                             'serve daemon under SERVICE_DIR and print its '
                             'per-tenant serving table (docs/serve.md)')
    parser.add_argument('--pod', metavar='DIR', default=None,
                        help='instead of reading a dataset, merge the '
                             'host-stamped JSONL exports under DIR and print '
                             'the pod report (per-host throughput/stall, '
                             'straggler callout); combine with --watch to '
                             're-render live')
    parser.add_argument('--fabric', metavar='DIR', default=None,
                        help='instead of reading a dataset, merge the fabric '
                             'client stats under the pod coordination dir DIR '
                             'and print the per-peer table: hits, fallbacks, '
                             'breaker state, mean fetch latency '
                             '(docs/fabric.md)')
    parser.add_argument('--postmortem', metavar='DIR', nargs='?', const='',
                        default=None,
                        help='instead of reading a dataset, merge the crash-'
                             'persistent flight files under DIR (default: the '
                             'PSTPU_FLIGHT_DIR run dir) and print the post-'
                             'mortem: per-process crash cause, dying stage, '
                             'windowed stall report (docs/troubleshooting.md)')
    parser.add_argument('--last', type=float, default=30.0, metavar='SECONDS',
                        help='with --postmortem: the stall-report window')
    parser.add_argument('--batch', metavar='TRACE_ID', default=None,
                        help="after the measured read, print the slowest-"
                             "batches table plus this batch's span tree and "
                             "critical path ('slowest' picks the worst; "
                             "implies --telemetry spans)")
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--batches', type=int, default=50)
    parser.add_argument('-p', '--pool-type', choices=('thread', 'process', 'dummy'),
                        default='thread')
    parser.add_argument('-w', '--workers-count', type=int, default=3)
    parser.add_argument('--batch-reader', action='store_true',
                        help='use make_batch_reader (plain Parquet stores)')
    parser.add_argument('--telemetry', choices=('counters', 'spans'), default='spans')
    parser.add_argument('--trace-out', default=None,
                        help='write a Perfetto-loadable Chrome trace JSON here')
    parser.add_argument('--prom-out', default=None,
                        help='write a Prometheus text exposition snapshot here')
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='print the report as JSON instead of text (in '
                             '--watch mode: one JSON line per tick)')
    parser.add_argument('--watch', type=float, default=None, metavar='SECONDS',
                        help='live mode: re-render the stall report from '
                             'windowed history every SECONDS instead of one '
                             'cumulative snapshot')
    parser.add_argument('--ticks', type=int, default=0,
                        help='with --watch: stop after this many rendered '
                             'ticks (0 = run until interrupted)')
    args = parser.parse_args(argv)

    if args.postmortem is not None:
        from petastorm_tpu.observability import blackbox
        run_dir = args.postmortem or blackbox.default_dir()
        if not os.path.isdir(run_dir):
            print('no flight directory at {} (was recording enabled? '
                  'PSTPU_FLIGHT_DIR relocates it)'.format(run_dir),
                  file=sys.stderr)
            return 1
        report = blackbox.postmortem_report(run_dir, last_s=args.last)
        if args.as_json:
            print(json.dumps(report, default=repr))
        else:
            print(blackbox.format_postmortem(report))
        return 0
    if args.fabric is not None:
        return diagnose_fabric(args.fabric, as_json=args.as_json)
    if args.serve is not None:
        return diagnose_serve(args.serve, as_json=args.as_json)
    if args.pod is not None:
        if args.watch is not None:
            watch_pod(args.pod, interval_s=args.watch, ticks=args.ticks or None,
                      as_json=args.as_json)
            return 0
        report = obs.pod_report(args.pod)
        if args.as_json:
            print(json.dumps({'pod': report, 'host': obs.host_identity()}))
        else:
            print(obs.format_pod_report(report))
        return 0
    if args.dataset_url is None:
        parser.error('dataset_url is required (or pass --serve SERVICE_DIR / '
                     '--pod DIR / --fabric DIR)')

    if args.watch is not None:
        watch(args.dataset_url, interval_s=args.watch,
              ticks=args.ticks or None, batch_size=args.batch_size,
              pool_type=args.pool_type, workers_count=args.workers_count,
              telemetry=args.telemetry, use_batch_reader=args.batch_reader,
              as_json=args.as_json)
        return 0

    telemetry = 'spans' if (args.trace_out or args.batch) else args.telemetry
    report, diag = diagnose(args.dataset_url, batch_size=args.batch_size,
                            batches=args.batches, pool_type=args.pool_type,
                            workers_count=args.workers_count, telemetry=telemetry,
                            use_batch_reader=args.batch_reader)
    # every snapshot names the host that measured it, so dumps collected
    # across a pod stay attributable after they leave the machine
    ident = obs.host_identity()
    if args.as_json:
        print(json.dumps({'host': ident, 'stall_report': report,
                          'fused_fallbacks': fused_fallback_table(diag),
                          'diagnostics': {k: v for k, v in sorted(diag.items())}}))
    else:
        print('host: {} (pid {})'.format(ident['host'], ident['pid']))
        print(obs.format_stall_report(report))
        fallbacks = format_fused_fallbacks(diag)
        if fallbacks:
            print(fallbacks)
        print('diagnostics:')
        for key in sorted(diag):
            print('  {} = {}'.format(key, diag[key]))
    if args.batch:
        show_batch(args.batch)
    if args.trace_out:
        n = obs.export_chrome_trace(args.trace_out)
        print('wrote {} trace events to {} (open in https://ui.perfetto.dev)'.format(
            n, args.trace_out))
    if args.prom_out:
        obs.write_prometheus(args.prom_out)
        print('wrote Prometheus exposition to {}'.format(args.prom_out))
    return 0


if __name__ == '__main__':
    _rc = main()
    # Hard exit after flushing: on images whose sitecustomize loads an
    # accelerator runtime plugin, interpreter finalization can race the
    # runtime's background threads and segfault AFTER all output is written
    # (observed intermittently in --watch mode), turning a successful run
    # into rc=-11 for scripts checking the exit code. The CLI's work is done
    # and flushed; skip teardown. In-process callers (tests, the Python API)
    # use main()/watch() directly and are unaffected.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_rc)
