"""``petastorm-tpu-diagnose``: one-shot pipeline health check for a dataset.

Runs a short measured read through the full loader pipeline with telemetry on
and prints the input-stall attribution report, the key pipeline counters, and
(optionally) a Chrome trace / Prometheus exposition dump::

    petastorm-tpu-diagnose file:///data/train --batches 50 \\
        --trace-out /tmp/pipeline.json --prom-out /tmp/metrics.prom

Open the trace in https://ui.perfetto.dev (or chrome://tracing). See
``docs/observability.md`` for how to read the output and
``docs/troubleshooting.md`` ("reading a stall report") for the remedies.
"""

from __future__ import annotations

import argparse
import json
import sys

from petastorm_tpu import observability as obs


def diagnose(dataset_url, batch_size=64, batches=50, pool_type='thread',
             workers_count=3, telemetry='spans', use_batch_reader=False,
             reader_kwargs=None):
    """Read ``batches`` batches and return ``(stall_report_dict, diagnostics)``."""
    from petastorm_tpu.jax.loader import JaxDataLoader

    obs.configure(telemetry)
    if use_batch_reader:
        from petastorm_tpu.reader import make_batch_reader as factory
        extra = {}
    else:
        from petastorm_tpu.reader import make_reader as factory
        extra = {'output': 'columnar'}
    reader = factory(dataset_url, reader_pool_type=pool_type,
                     workers_count=workers_count, num_epochs=None,
                     telemetry=telemetry, **dict(extra, **(reader_kwargs or {})))
    # the loader context owns the reader: its exit stops and joins it
    with JaxDataLoader(reader, batch_size=batch_size, drop_last=False) as loader:
        it = iter(loader)
        for _ in range(batches):
            next(it)
        diag = loader.diagnostics
        return obs.stall_report(diag), diag


def fused_fallback_table(diagnostics):
    """``{column: {reason: count}}`` parsed from the labelled
    ``fused_fallback_column:<col>:<reason>`` counters — the per-column answer
    to "why is this column still on the Arrow path" (docs/native.md lists the
    reason catalog). Empty when every requested column fused (or the store
    predates the counters)."""
    table = {}
    for key, value in diagnostics.items():
        if not key.startswith('fused_fallback_column:'):
            continue
        try:
            _prefix, column, reason = key.split(':', 2)
        except ValueError:
            continue
        table.setdefault(column, {})[reason] = int(value)
    return table


def format_fused_fallbacks(diagnostics):
    """Human-readable per-column fallback section (empty string when every
    column rode the fused/zero-copy native path)."""
    table = fused_fallback_table(diagnostics)
    if not table:
        return ''
    lines = ['fused-decode fallbacks (column -> reason x count; see '
             'docs/native.md for the reason catalog):']
    for column in sorted(table):
        reasons = ', '.join('{} x{}'.format(r, c)
                            for r, c in sorted(table[column].items()))
        lines.append('  {:<24s} {}'.format(column, reasons))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-diagnose',
        description='Measure a short read of the dataset and attribute input '
                    'stalls to pipeline stages.')
    parser.add_argument('dataset_url')
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--batches', type=int, default=50)
    parser.add_argument('-p', '--pool-type', choices=('thread', 'process', 'dummy'),
                        default='thread')
    parser.add_argument('-w', '--workers-count', type=int, default=3)
    parser.add_argument('--batch-reader', action='store_true',
                        help='use make_batch_reader (plain Parquet stores)')
    parser.add_argument('--telemetry', choices=('counters', 'spans'), default='spans')
    parser.add_argument('--trace-out', default=None,
                        help='write a Perfetto-loadable Chrome trace JSON here')
    parser.add_argument('--prom-out', default=None,
                        help='write a Prometheus text exposition snapshot here')
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='print the report as JSON instead of text')
    args = parser.parse_args(argv)

    telemetry = 'spans' if args.trace_out else args.telemetry
    report, diag = diagnose(args.dataset_url, batch_size=args.batch_size,
                            batches=args.batches, pool_type=args.pool_type,
                            workers_count=args.workers_count, telemetry=telemetry,
                            use_batch_reader=args.batch_reader)
    if args.as_json:
        print(json.dumps({'stall_report': report,
                          'fused_fallbacks': fused_fallback_table(diag),
                          'diagnostics': {k: v for k, v in sorted(diag.items())}}))
    else:
        print(obs.format_stall_report(report))
        fallbacks = format_fused_fallbacks(diag)
        if fallbacks:
            print(fallbacks)
        print('diagnostics:')
        for key in sorted(diag):
            print('  {} = {}'.format(key, diag[key]))
    if args.trace_out:
        n = obs.export_chrome_trace(args.trace_out)
        print('wrote {} trace events to {} (open in https://ui.perfetto.dev)'.format(
            n, args.trace_out))
    if args.prom_out:
        obs.write_prometheus(args.prom_out)
        print('wrote Prometheus exposition to {}'.format(args.prom_out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
