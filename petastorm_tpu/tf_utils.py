"""TensorFlow adapter — capability parity with the reference's ``tf_utils``
(/root/reference/petastorm/tf_utils.py): numpy->tf dtype promotion (:27-44),
value sanitization (:58-97), ``make_petastorm_dataset`` via
``tf.data.Dataset.from_generator`` (:348-402). The graph-mode ``tf_tensors``
py_func pump is intentionally not reproduced — it exists for TF1 sessions; this
framework targets eager tf.data only (and, primarily, the JAX loader).

TensorFlow is imported lazily so the rest of the framework works without it.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np


def _tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError:
        raise ImportError('make_petastorm_dataset requires tensorflow; it is not installed. '
                          'Use petastorm_tpu.jax.JaxDataLoader (primary) or '
                          'petastorm_tpu.torch_utils.DataLoader instead.')


def _sanitize_field_value(value):
    """Promotions mirroring reference tf_utils.py:27-97: uint16->int32,
    uint32->int64, Decimal->string, datetime64->int64 ns."""
    if isinstance(value, Decimal):
        return str(value)
    if isinstance(value, np.datetime64):
        return value.astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.ndarray):
        if value.dtype == np.uint16:
            return value.astype(np.int32)
        if value.dtype in (np.uint32,):
            return value.astype(np.int64)
        if np.issubdtype(value.dtype, np.datetime64):
            return value.astype('datetime64[ns]').astype(np.int64)
        if value.dtype == object and value.size and isinstance(value.flat[0], Decimal):
            return value.astype(str)
    if isinstance(value, np.generic):
        if value.dtype == np.uint16:
            return np.int32(value)
        if value.dtype == np.uint32:
            return np.int64(value)
    return value


def make_petastorm_dataset(reader):
    """Wrap a reader in a ``tf.data.Dataset`` yielding row namedtuples (or
    column-batch namedtuples for batched readers), reference tf_utils.py:348-402."""
    tf = _tf()

    if getattr(reader, 'ngram', None) is not None:
        raise NotImplementedError(
            'NGram readers are not supported by make_petastorm_dataset (the reference '
            'tf adapter refuses too, tf_utils.py:404); use the JAX loader, which batches '
            'NGram windows natively.')
    schema = reader.transformed_schema

    def generator():
        for item in reader:
            yield tuple(_sanitize_field_value(v) for v in item)

    # derive output signature from one sample row (shapes with None wildcards)
    field_names = list(schema.fields)
    signature = []
    for name in field_names:
        field = schema.fields[name]
        if field.numpy_dtype is Decimal or field.numpy_dtype in (np.str_, np.bytes_):
            tf_dtype = tf.string
        elif field.numpy_dtype is np.datetime64:
            tf_dtype = tf.int64
        elif np.dtype(field.numpy_dtype) == np.uint16:
            tf_dtype = tf.int32
        elif np.dtype(field.numpy_dtype) == np.uint32:
            tf_dtype = tf.int64
        else:
            tf_dtype = tf.as_dtype(np.dtype(field.numpy_dtype))
        shape = field.shape
        if reader.batched_output:
            shape = (None,) + tuple(shape or ())
        signature.append(tf.TensorSpec(shape=shape, dtype=tf_dtype))

    dataset = tf.data.Dataset.from_generator(generator, output_signature=tuple(signature))
    namedtuple_type = schema.namedtuple
    return dataset.map(lambda *args: namedtuple_type(*args))


class make_tf_dataset_context(object):
    """Context manager: fixed-``batch_size`` ``tf.data.Dataset`` over a batched
    reader, closing the reader on exit (the converter's
    ``SparkDatasetConverter.make_tf_dataset`` surface, reference
    spark/spark_dataset_converter.py:142-172,224-274)."""

    def __init__(self, reader, batch_size=32, prefetch=None):
        self._reader = reader
        self._batch_size = batch_size
        self._prefetch = prefetch

    def __enter__(self):
        try:
            tf = _tf()
            dataset = make_petastorm_dataset(self._reader)
            if self._reader.batched_output:
                # row-group batches -> fixed-size batches
                dataset = dataset.unbatch()
            dataset = dataset.batch(self._batch_size)
            if self._prefetch != 0:
                dataset = dataset.prefetch(self._prefetch or tf.data.AUTOTUNE)
            return dataset
        except Exception:
            # __exit__ never runs when __enter__ raises: don't leak the pool
            self._reader.stop()
            self._reader.join()
            raise

    def __exit__(self, exc_type, exc_value, tb):
        self._reader.stop()
        self._reader.join()
