"""TensorFlow adapter — capability parity with the reference's ``tf_utils``
(/root/reference/petastorm/tf_utils.py): numpy->tf dtype promotion (:27-44),
value sanitization (:58-97), ``make_petastorm_dataset`` via
``tf.data.Dataset.from_generator`` (:348-402), NGram flattening to
per-timestep namedtuples (:141-183,254-286), and client-side shuffling
(:201-219 — the TF1 ``tf.RandomShuffleQueue`` is replaced by the framework's
seedable shuffling buffer inside the generator; batched readers refuse it,
:327-331). The graph-mode ``tf_tensors`` py_func pump is intentionally not
reproduced — it exists for TF1 sessions; this framework targets eager tf.data
only (and, primarily, the JAX loader).

TensorFlow is imported lazily so the rest of the framework works without it.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np


def _tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError:
        raise ImportError('make_petastorm_dataset requires tensorflow; it is not installed. '
                          'Use petastorm_tpu.jax.JaxDataLoader (primary) or '
                          'petastorm_tpu.torch_utils.DataLoader instead.')


def _sanitize_field_value(value):
    """Promotions mirroring reference tf_utils.py:27-97: uint16->int32,
    uint32->int64, Decimal->string, datetime64->int64 ns."""
    if isinstance(value, Decimal):
        return str(value)
    if isinstance(value, np.datetime64):
        return value.astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.ndarray):
        if value.dtype == np.uint16:
            return value.astype(np.int32)
        if value.dtype in (np.uint32,):
            return value.astype(np.int64)
        if np.issubdtype(value.dtype, np.datetime64):
            return value.astype('datetime64[ns]').astype(np.int64)
        if value.dtype == object and value.size and isinstance(value.flat[0], Decimal):
            return value.astype(str)
    if isinstance(value, np.generic):
        if value.dtype == np.uint16:
            return np.int32(value)
        if value.dtype == np.uint32:
            return np.int64(value)
    return value


def _tf_spec(tf, field, batched):
    """TensorSpec for one field, applying the reference's dtype promotions."""
    if field.numpy_dtype is Decimal or field.numpy_dtype in (np.str_, np.bytes_):
        tf_dtype = tf.string
    elif field.numpy_dtype is np.datetime64:
        tf_dtype = tf.int64
    elif np.dtype(field.numpy_dtype) == np.uint16:
        tf_dtype = tf.int32
    elif np.dtype(field.numpy_dtype) == np.uint32:
        tf_dtype = tf.int64
    else:
        tf_dtype = tf.as_dtype(np.dtype(field.numpy_dtype))
    shape = field.shape
    if batched:
        shape = (None,) + tuple(shape or ())
    return tf.TensorSpec(shape=shape, dtype=tf_dtype)


def _shuffled(reader, shuffle_buffer_size, seed):
    """Iterate the reader through a seedable client-side shuffling buffer —
    the eager replacement for the reference's TF1 ``tf.RandomShuffleQueue``
    (tf_utils.py:201-219)."""
    from petastorm_tpu.shuffling_buffer import RandomShufflingBuffer

    if shuffle_buffer_size < 2:
        # a 1-slot buffer cannot decorrelate anything; pass rows straight through
        # instead of tripping RandomShufflingBuffer's min_after_retrieve < capacity check
        yield from reader
        return
    buf = RandomShufflingBuffer(shuffle_buffer_size,
                                min_after_retrieve=max(1, shuffle_buffer_size // 2),
                                extra_capacity=max(1000, shuffle_buffer_size), seed=seed)
    for item in reader:
        buf.add_many([item])
        while buf.can_retrieve():
            yield buf.retrieve()
    buf.finish()
    while buf.can_retrieve():
        yield buf.retrieve()


def make_petastorm_dataset(reader, shuffle_buffer_size=0, seed=None):
    """Wrap a reader in a ``tf.data.Dataset`` (reference tf_utils.py:348-402).

    Elements are row namedtuples; column-batch namedtuples for batched readers;
    for NGram readers, dicts of ``offset -> per-timestep namedtuple`` (the
    reference's NGram flattening, tf_utils.py:141-183,254-286).

    ``shuffle_buffer_size > 0`` decorrelates rows with the framework's seedable
    shuffling buffer before they enter the TF graph; batched readers reject it
    because whole row groups would shuffle as units (reference
    tf_utils.py:327-331).
    """
    tf = _tf()
    if reader.batched_output and getattr(reader, 'ngram', None) is not None:
        raise ValueError(
            'make_petastorm_dataset does not support columnar NGram readers (nested '
            "window blocks); use make_reader(output='rows', ngram=...) for the TF "
            'surface, or JaxDataLoader for the columnar window path.')
    ngram = getattr(reader, 'ngram', None)

    if shuffle_buffer_size and reader.batched_output:
        raise ValueError(
            'shuffle_buffer_size is not supported with batched readers: whole row-group '
            'batches would shuffle as units (reference tf_utils.py:327-331). Shuffle via '
            'make_reader shuffle_row_groups/shuffle_row_drop_partitions, or use '
            'dataset.unbatch().shuffle(...).')
    schema = reader.transformed_schema

    def rows():
        if shuffle_buffer_size:
            return _shuffled(reader, shuffle_buffer_size, seed)
        return iter(reader)

    if ngram is not None:
        offsets = sorted(ngram.fields)
        views = {off: ngram.get_schema_at_timestep(schema, off) for off in offsets}
        signature = {off: tuple(_tf_spec(tf, views[off].fields[n], False)
                                for n in views[off].fields)
                     for off in offsets}

        def generator():
            for window in rows():
                yield {off: tuple(_sanitize_field_value(v) for v in window[off])
                       for off in offsets}

        dataset = tf.data.Dataset.from_generator(generator, output_signature=signature)
        view_namedtuples = {off: views[off].namedtuple for off in offsets}
        return dataset.map(
            lambda window: {off: view_namedtuples[off](*window[off]) for off in offsets})

    signature = tuple(_tf_spec(tf, schema.fields[name], reader.batched_output)
                      for name in schema.fields)

    def generator():
        for item in rows():
            yield tuple(_sanitize_field_value(v) for v in item)

    dataset = tf.data.Dataset.from_generator(generator, output_signature=signature)
    namedtuple_type = schema.namedtuple
    return dataset.map(lambda *args: namedtuple_type(*args))


class make_tf_dataset_context(object):
    """Context manager: fixed-``batch_size`` ``tf.data.Dataset`` over a batched
    reader, closing the reader on exit (the converter's
    ``SparkDatasetConverter.make_tf_dataset`` surface, reference
    spark/spark_dataset_converter.py:142-172,224-274)."""

    def __init__(self, reader, batch_size=32, prefetch=None):
        self._reader = reader
        self._batch_size = batch_size
        self._prefetch = prefetch

    def __enter__(self):
        try:
            tf = _tf()
            dataset = make_petastorm_dataset(self._reader)
            if self._reader.batched_output:
                # row-group batches -> fixed-size batches
                dataset = dataset.unbatch()
            dataset = dataset.batch(self._batch_size)
            if self._prefetch != 0:
                dataset = dataset.prefetch(self._prefetch or tf.data.AUTOTUNE)
            return dataset
        except Exception:
            # __exit__ never runs when __enter__ raises: don't leak the pool
            self._reader.stop()
            self._reader.join()
            raise

    def __exit__(self, exc_type, exc_value, tb):
        self._reader.stop()
        self._reader.join()
