"""Filesystem resolution: dataset URL -> (pyarrow filesystem, path).

Parity: reference ``FilesystemResolver`` (/root/reference/petastorm/fs_utils.py:23-185)
and the HDFS/GCS helper packages. We ride on ``pyarrow.fs`` (Arrow C++ filesystems),
which natively covers local, HDFS, S3 and GCS — the reference predates these and
hand-rolled wrappers around libhdfs3/s3fs/gcsfs.

Scheme-less URLs are rejected, as in the reference (fs_utils.py:32-41), to avoid
ambiguity between local paths and default-FS paths.
"""

from __future__ import annotations

import os
from urllib.parse import urlparse

import pyarrow.fs as pafs

from petastorm_tpu.errors import PetastormTpuError


class FilesystemResolver(object):
    """Resolves a dataset URL into a ``pyarrow.fs.FileSystem`` + in-filesystem path.

    Supported schemes: ``file://``, ``hdfs://``, ``s3://``, ``gs://``/``gcs://``,
    plus ``mock-remote://`` (local files treated as a remote store — tests and
    benches of the remote paths). A picklable factory is exposed for worker
    processes (reference fs_utils.py:174-180).
    """

    def __init__(self, dataset_url, retry_policy=None):
        """``retry_policy``: a :class:`petastorm_tpu.retry.RetryPolicy`
        governing transient-error retries on object-store IO; ``None`` =
        defaults for ``s3://``/``gs://`` (where throttles/resets are expected
        operating conditions), ``False`` = no retry wrapping."""
        if not isinstance(dataset_url, str):
            raise PetastormTpuError('dataset_url must be a string, got {}'.format(type(dataset_url)))
        dataset_url = dataset_url.rstrip('/')
        parsed = urlparse(dataset_url)
        if not parsed.scheme:
            raise PetastormTpuError(
                'URL {!r} has no scheme. Use file://<absolute path> for local datasets '
                '(e.g. file:///tmp/my_dataset), or hdfs://, s3://, gs://.'.format(dataset_url))
        self._url = dataset_url
        self._scheme = parsed.scheme
        self._retry_policy = retry_policy
        if parsed.scheme == 'file':
            if parsed.netloc not in ('', 'localhost'):
                raise PetastormTpuError('file:// URL must not have a host: {}'.format(dataset_url))
            self._path = parsed.path
            self._filesystem = pafs.LocalFileSystem()
        elif parsed.scheme in ('gs', 'gcs'):
            self._filesystem = _wrap_object_store(pafs.GcsFileSystem(), retry_policy)
            self._path = parsed.netloc + parsed.path
        elif parsed.scheme == 's3':
            self._filesystem = _wrap_object_store(pafs.S3FileSystem(), retry_policy)
            self._path = parsed.netloc + parsed.path
        elif parsed.scheme == 'mock-remote':
            # test/bench-only scheme: the LOCAL filesystem behind the same
            # retry wrapper the object stores get, so every remote-only code
            # path (retrying streams, chunk store, pre_buffer reads) is
            # exercised hermetically without a cloud credential
            if parsed.netloc not in ('', 'localhost'):
                raise PetastormTpuError(
                    'mock-remote:// URL must not have a host: {}'.format(dataset_url))
            self._filesystem = _wrap_object_store(pafs.LocalFileSystem(), retry_policy)
            self._path = parsed.path
        elif parsed.scheme == 'hdfs':
            # HDFS elasticity is the HA namenode failover in hdfs/namenode.py,
            # the reference's model; no backoff wrapper on top
            self._filesystem, self._path = _resolve_hdfs(dataset_url)
        else:
            raise PetastormTpuError('Unsupported URL scheme {!r} in {}'.format(parsed.scheme, dataset_url))

    @property
    def url(self):
        return self._url

    @property
    def scheme(self):
        return self._scheme

    @property
    def is_local(self):
        """True when the dataset is plain local files (``file://``) — mmap-able
        directly, so byte-mirroring caches (the chunk store) have nothing to
        add. ``mock-remote://`` deliberately reports False: it exists to
        exercise the remote paths."""
        return self._scheme == 'file'

    def filesystem(self):
        return self._filesystem

    def get_dataset_path(self):
        return self._path

    def filesystem_factory(self):
        """A picklable zero-arg callable recreating the filesystem — including
        the retry policy — in another process (pyarrow filesystems themselves
        are picklable in modern Arrow, but a URL-based factory stays robust
        across versions). A custom ``classify`` callable on the policy must be
        picklable (module-level) to cross a process-pool boundary."""
        return _FilesystemFactory(self._url, self._retry_policy)

    def __getstate__(self):
        return {'url': self._url, 'retry_policy': self._retry_policy}

    def __setstate__(self, state):
        self.__init__(state['url'], retry_policy=state.get('retry_policy'))


def _wrap_object_store(fs, retry_policy):
    """Object stores answer transiently (429/503 throttles, resets) as a
    normal operating condition: wrap in the bounded-backoff retrier unless
    explicitly disabled (``retry_policy=False``)."""
    if retry_policy is False:
        return fs
    from petastorm_tpu.retry import wrap_retrying
    return wrap_retrying(fs, retry_policy)


def _resolve_hdfs(dataset_url):
    """hdfs:// URL -> (filesystem, path). When the URL's netloc is a configured
    HA nameservice (or empty -> fs.defaultFS), returns an HA-failover client
    wrapped as a genuine pyarrow filesystem; otherwise falls back to Arrow's
    own URI handling (libhdfs 'default' filesystem, direct host connects)."""
    from petastorm_tpu.hdfs import namenode as nn

    try:
        return nn.resolve_and_connect(dataset_url, pyarrow_wrap=True)
    except nn.HdfsConnectError:
        # resolution succeeded but every namenode refused: that diagnosis
        # (per-namenode errors) is the actionable one — don't mask it
        raise
    except (RuntimeError, IOError):
        # no/incomplete Hadoop config: let Arrow's own URI handling try —
        # libhdfs reads CLASSPATH config itself and understands hdfs:///
        return pafs.FileSystem.from_uri(dataset_url)


class _FilesystemFactory(object):
    """Picklable zero-arg filesystem factory (spawned worker processes re-resolve
    the URL instead of shipping a live filesystem handle)."""

    def __init__(self, url, retry_policy=None):
        self._url = url
        self._retry_policy = retry_policy

    def __call__(self):
        return FilesystemResolver(self._url, retry_policy=self._retry_policy).filesystem()


def path_to_url(path):
    """Convenience: absolute local path -> file:// URL."""
    return 'file://' + os.path.abspath(path)


def resolve_dataset_url(dataset_url):
    """Resolve a URL to ``(filesystem, path)``."""
    resolver = FilesystemResolver(dataset_url)
    return resolver.filesystem(), resolver.get_dataset_path()
