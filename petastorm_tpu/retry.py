"""Transient-storage retry: bounded exponential backoff around object-store IO.

The reference's only storage elasticity is HDFS namenode failover
(/root/reference/petastorm/hdfs/namenode.py:236-271, mirrored here in
``hdfs/namenode.py``). Object stores (``s3://``, ``gs://``) fail differently:
not a standby endpoint to fail over to, but the SAME endpoint answering
transiently with throttles (429/503 SlowDown), connection resets, and
timeouts — the expected behavior of a TPU-scale input pipeline hammering GCS
from many hosts. This module is the cloud-native analog of the failover
decorator: every filesystem operation and positional read gets a bounded
exponential-backoff retry with decorrelated jitter, and a fresh underlying
stream is opened when a read fails mid-flight (SURVEY §2.9 elasticity row).

Policy: retries apply to idempotent operations only — metadata calls, input
opens and reads, plus create_dir/copy_file (re-running converges). Deletes
and moves pass through unretried (success-then-lost-response would make the
retry raise a spurious FileNotFoundError). Output streams are NOT retried
mid-write (a half-written object is not safely resumable); only their open is.

Cost: input files route through ``pa.PythonFile`` so mid-read failures can
resume on a fresh stream — a per-read Python hop (~µs, GIL-held) on schemes
where a single network round trip costs milliseconds. The wrapper is applied
ONLY to s3/gs; local-file reads (the duty-cycle hot path) never see it.
"""

from __future__ import annotations

import errno
import logging
import random
import re
import time

import pyarrow as pa
import pyarrow.fs as pafs

from petastorm_tpu.pafs_util import DelegatingHandler

logger = logging.getLogger(__name__)

#: fault-injection hook (``petastorm_tpu.faults``): when armed, invoked before
#: every :meth:`RetryPolicy.call` attempt so seeded chaos runs can exercise
#: the transient-backoff path; None (the production state) costs one global
#: load per retried operation — storage ops, never per row
FAULT_POINT = None

#: errnos that signal a transient network/storage condition
_TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.ETIMEDOUT, errno.ECONNRESET, errno.ECONNABORTED,
    errno.ECONNREFUSED, errno.EPIPE, errno.EHOSTUNREACH, errno.ENETUNREACH,
    errno.EBUSY,
})

#: lower-cased substrings of error messages Arrow surfaces for retryable
#: object-store failures (Arrow folds HTTP-level errors into OSError text)
_TRANSIENT_MARKERS = (
    'slow down', 'slowdown', 'slow_down', 'too many requests', 'request rate',
    'timed out', 'timeout', 'connection reset', 'connection aborted',
    'connection refused', 'broken pipe', 'temporarily unavailable',
    'service unavailable', 'internal server error',
    'bad gateway', 'gateway timeout', 'eof occurred',
    'curl error', 'throttl',
    # a ranged GET whose body came back truncated (fetch_range raises this
    # text): the transfer broke mid-flight — retry on a fresh stream
    'short read',
)

#: retryable HTTP status codes, matched only in status context — a bare
#: " 500" would also match byte counts in permanent errors ("got 500 bytes")
_TRANSIENT_HTTP_RE = re.compile(
    r'(?:http|status|code|error)\W{0,10}(?:429|500|502|503|504)\b')


def is_transient_io_error(exc):
    """Classify an exception as a retryable transient storage failure.

    Conservative on purpose: FileNotFoundError/PermissionError and schema or
    parse errors must fail immediately — retrying them only delays the real
    diagnosis.
    """
    if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError)):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError):
        if exc.errno in _TRANSIENT_ERRNOS:
            return True
        msg = str(exc).lower()
        return (any(marker in msg for marker in _TRANSIENT_MARKERS)
                or _TRANSIENT_HTTP_RE.search(msg) is not None)
    return False


class RetryPolicy(object):
    """Bounded exponential backoff with decorrelated jitter.

    ``max_attempts`` counts the initial try: 4 means up to 3 retries. Sleeps
    follow ``initial_backoff_s * multiplier**k`` capped at ``max_backoff_s``,
    each scaled by ``1 ± jitter`` so synchronized workers do not re-stampede
    the endpoint that just throttled them.

    ``deadline_s`` is an optional END-TO-END budget per :meth:`call`: once the
    total elapsed time plus the next backoff sleep would exceed it, the retry
    loop stops and re-raises the final error instead of burning the remaining
    attempt count. Callers on a latency budget (the fabric's degraded
    object-store fallback, anything feeding an accelerator step) bound their
    worst case without giving up the early retries that usually succeed.
    """

    def __init__(self, max_attempts=4, initial_backoff_s=0.1, multiplier=2.0,
                 max_backoff_s=5.0, jitter=0.25, classify=is_transient_io_error,
                 deadline_s=None):
        if max_attempts < 1:
            raise ValueError('max_attempts must be >= 1, got {}'.format(max_attempts))
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError('deadline_s must be positive, got {!r}'.format(deadline_s))
        self.max_attempts = max_attempts
        self.initial_backoff_s = initial_backoff_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.classify = classify
        self.deadline_s = deadline_s

    def with_deadline(self, deadline_s):
        """A copy of this policy under an end-to-end ``deadline_s`` budget
        (``None`` removes the budget)."""
        return RetryPolicy(max_attempts=self.max_attempts,
                           initial_backoff_s=self.initial_backoff_s,
                           multiplier=self.multiplier,
                           max_backoff_s=self.max_backoff_s,
                           jitter=self.jitter, classify=self.classify,
                           deadline_s=deadline_s)

    def _key(self):
        return (self.max_attempts, self.initial_backoff_s, self.multiplier,
                self.max_backoff_s, self.jitter, self.classify, self.deadline_s)

    def __eq__(self, other):
        return type(self) is type(other) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def backoff_s(self, attempt):
        """Sleep before retry number ``attempt`` (1-based)."""
        base = min(self.initial_backoff_s * self.multiplier ** (attempt - 1),
                   self.max_backoff_s)
        return base * (1.0 + self.jitter * (2.0 * random.random() - 1.0))

    def call(self, fn, *args, on_retry=None, **kwargs):
        """Invoke ``fn`` with retries per this policy. ``on_retry`` (if given)
        runs after each backoff sleep, before the re-attempt — e.g. reopening
        a broken stream."""
        attempt = 1
        t0 = time.monotonic() if self.deadline_s is not None else None
        while True:
            try:
                if FAULT_POINT is not None:
                    FAULT_POINT()
                return fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classifier decides
                if attempt >= self.max_attempts or not self.classify(e):
                    raise
                sleep_s = self.backoff_s(attempt)
                if t0 is not None and \
                        (time.monotonic() - t0) + sleep_s > self.deadline_s:
                    # the end-to-end budget is spent: sleeping and retrying
                    # would blow the deadline — surface the final error now
                    raise
                logger.warning('Transient storage error (attempt %d/%d, retrying in %.2fs): %s',
                               attempt, self.max_attempts, sleep_s, e)
                time.sleep(sleep_s)
                attempt += 1
                if on_retry is not None:
                    on_retry()


class _RetryingInputFile(object):
    """File-like over ``fs.open_input_file`` that survives mid-read transient
    failures by reopening the underlying stream and seeking back to the last
    good position. Wrapped in ``pa.PythonFile`` so Arrow/Parquet C++ consume it
    as a random-access file."""

    def __init__(self, fs, path, policy):
        self._fs = fs
        self._path = path
        self._policy = policy
        self._pos = 0
        self._file = policy.call(fs.open_input_file, path)
        self._size = None

    def _reopen(self):
        try:
            self._file.close()
        except Exception:  # noqa: BLE001 — old handle is already broken
            pass
        self._file = self._fs.open_input_file(self._path)
        self._file.seek(self._pos)

    def _with_stream_retry(self, op):
        # a failed read leaves the stream in an unknown state: always resume
        # on a FRESH stream at the last good offset
        return self._policy.call(op, on_retry=lambda: self._policy.call(self._reopen))

    # --- file protocol consumed by pa.PythonFile ---

    def read(self, nbytes=None):
        def _do():
            self._file.seek(self._pos)
            data = self._file.read(nbytes) if nbytes is not None else self._file.read()
            return data
        data = self._with_stream_retry(_do)
        self._pos += len(data)
        return data

    def seek(self, offset, whence=0):
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self.size() + offset
        else:
            raise ValueError('invalid whence {}'.format(whence))
        return self._pos

    def tell(self):
        return self._pos

    def size(self):
        if self._size is None:
            self._size = self._with_stream_retry(lambda: self._file.size())
        return self._size

    @property
    def closed(self):
        return self._file.closed

    def close(self):
        self._file.close()


class RetryingHandler(DelegatingHandler):
    """A ``pyarrow.fs.FileSystemHandler`` delegating to another pyarrow
    filesystem with transient-error retries on idempotent operations: every
    delegated op retries per the policy; input opens additionally return
    mid-read-resumable streams; output streams retry the OPEN only (a
    half-written object store upload is not safely resumable, so mid-write
    failures must surface).

    Use ``wrap_retrying(fs)`` to obtain a real ``pyarrow.fs.PyFileSystem``
    usable anywhere a filesystem is (parquet reads, dataset discovery).
    """

    def __init__(self, fs, policy=None):
        super(RetryingHandler, self).__init__(fs)
        self.policy = policy or RetryPolicy()

    def __eq__(self, other):
        # pyarrow dataset machinery dedupes on filesystem equality: the same
        # store under DIFFERENT retry policies must not compare equal
        if type(other) is type(self):
            return self.fs == other.fs and self.policy == other.policy
        return NotImplemented

    def __hash__(self):
        # defining __eq__ alone sets __hash__ = None — the handler AND any
        # pyarrow.fs.PyFileSystem wrapping it would become unhashable (PT600).
        # self.fs stays out of the tuple: pyarrow FileSystems are themselves
        # unhashable; same-policy handlers over different stores merely collide
        return hash((type(self), self.policy))

    def _invoke(self, fn, *args, **kwargs):
        return self.policy.call(fn, *args, **kwargs)

    def get_type_name(self):
        return 'retrying+' + self.fs.type_name

    # non-idempotent mutations pass through UNretried: if the server performed
    # the op but the response was lost, a retry would surface a spurious
    # FileNotFoundError for an operation that actually succeeded. (create_dir
    # and copy_file stay retried — re-running them converges to the same state.)

    def delete_file(self, path):
        self.fs.delete_file(path)

    def delete_dir(self, path):
        self.fs.delete_dir(path)

    def delete_dir_contents(self, path, missing_dir_ok=False):
        self.fs.delete_dir_contents(path, missing_dir_ok=missing_dir_ok)

    def delete_root_dir_contents(self):
        self.fs.delete_dir_contents('/', accept_root_dir=True)

    def move(self, src, dest):
        self.fs.move(src, dest)

    def open_input_stream(self, path):
        return pa.PythonFile(_RetryingInputFile(self.fs, path, self.policy), mode='r')

    def open_input_file(self, path):
        return pa.PythonFile(_RetryingInputFile(self.fs, path, self.policy), mode='r')


def wrap_retrying(fs, policy=None):
    """Wrap a pyarrow filesystem so transient IO errors are retried with
    bounded exponential backoff. Returns a genuine ``pyarrow.fs.PyFileSystem``."""
    return pafs.PyFileSystem(RetryingHandler(fs, policy))


def fetch_range(fs, path, offset, length, policy=None, deadline_s=None):
    """Read exactly ``[offset, offset + length)`` of ``path`` as ONE retried
    unit: each attempt opens a FRESH stream (a positional read that failed
    leaves an object-store stream in an unknown state), reads the range, and
    closes it. A short body raises and is classified transient, so a truncated
    transfer retries instead of caching garbage.

    ``deadline_s`` (optional) bounds the whole retried fetch end to end — the
    fabric's degraded fallback path passes its remaining transfer budget here
    so a throttling object store cannot stall a batch past the deadline.

    This is the chunk store's fetch primitive. ``fs`` may be raw or already
    retry-wrapped — in the wrapped case the inner ops retry individually too,
    which only tightens the elasticity."""
    policy = policy or RetryPolicy()
    if deadline_s is not None:
        policy = policy.with_deadline(deadline_s)

    def _attempt():
        f = fs.open_input_file(path)
        try:
            if hasattr(f, 'read_at'):
                data = f.read_at(length, offset)
            else:
                f.seek(offset)
                data = f.read(length)
        finally:
            f.close()
        if len(data) != length:
            raise IOError('short read: got {} of {} bytes at offset {} from {}'.format(
                len(data), length, offset, path))
        return bytes(data)

    return policy.call(_attempt)
