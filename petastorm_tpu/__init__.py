"""petastorm_tpu: a TPU-native Parquet data access framework for deep learning.

Capability parity with petastorm (reference mounted at /root/reference), built
TPU-first: datasets materialize to Parquet with a unified schema+codec system and
read back through parallel prefetch/decode worker pools into sharded ``jax.Array``
batches staged onto a TPU mesh.

Top-level API mirrors the reference (petastorm/__init__.py:15-19):
``make_reader``, ``make_batch_reader``, ``TransformSpec``, ``NoDataAvailableError``.
"""

from petastorm_tpu.autotune import AutotuneConfig  # noqa: F401
from petastorm_tpu.errors import NoDataAvailableError  # noqa: F401
from petastorm_tpu.transform import TransformSpec  # noqa: F401

from petastorm_tpu.reader import (make_reader, make_batch_reader,  # noqa: F401
                                  merge_resume_states)

__version__ = '0.1.0'
