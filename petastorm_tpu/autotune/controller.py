"""The feedback controller: windowed stall evidence in, bounded knob moves out.

The control loop (one :meth:`Autotuner.tick` per ``interval_s``):

1. snapshot diagnostics into the :class:`HistoryRecorder`;
2. compute the tick-to-tick **window delta** and its windowed stall report —
   attribution of the *last interval's* wait, not the run's cumulative total;
3. decide (:meth:`Autotuner.evaluate`): a stalled window names its bottleneck
   and the bottleneck names the knob — grow the worker pool, raise the chunk
   prefetch in-flight budget, shrink the shuffle buffer; a persistently calm
   pipeline gives a grown worker slot back;
4. act, **always** through :meth:`_apply`-style code that (a) clamps the
   target into the config's explicit ``[min, max]`` (lint rule PT702 rejects
   an unclamped knob write anywhere in this package), (b) runs inside a
   ``decision_span`` so the change lands in the trace ring as an
   ``autotune.decision`` event, and (c) appends a structured record — with
   the evidence window attached — to :attr:`Autotuner.decisions` and the
   JSONL :class:`DecisionLog`.

Safety comes from three layers of hysteresis (see ``docs/autotune.md``):
a per-knob cooldown between moves, a longer cooldown before *reversing* a
knob's direction, and a freeze after repeated reversals — alternating
bottlenecks therefore cannot thrash a knob (the oscillation-guard test in
``tests/test_autotune.py``). Worker-pool moves are additionally safe by
construction: growth spawns a fresh supervised slot, shrink retires an idle
slot through the same death-handling path a crash takes, so the exactly-once
delivery guarantees of ``docs/protocol.md`` hold across every resize.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from petastorm_tpu import observability as obs
from petastorm_tpu.observability import history as _history
from petastorm_tpu.observability import trace as _trace

logger = logging.getLogger(__name__)

#: stall-report bottlenecks answered by growing the worker pool
_WORKER_BOTTLENECKS = frozenset({
    'worker.decode', 'worker.fused_decode', 'worker.transform',
    'worker.read_io', 'pool.unattributed'})


def clamp(value, lo, hi):
    """Bound a knob target into ``[lo, hi]`` — the ONE clamp every knob write
    must pass through (lint rule PT702)."""
    if lo is not None and value < lo:
        return lo
    if hi is not None and value > hi:
        return hi
    return value


class decision_span(object):
    """Context manager recording one ``autotune.decision`` Chrome-trace event.

    Unlike :func:`petastorm_tpu.observability.span`, the event records at
    EVERY telemetry level: decisions are rare (hysteresis bounds them to at
    most one per knob per cooldown) and each one must stay explainable in an
    exported trace even when per-stage spans are off. ``note()`` adds fields
    (e.g. the post-clamp target) before the span closes.
    """

    __slots__ = ('args', '_wall0', '_t0')

    def __init__(self, **args):
        self.args = args

    def note(self, **kwargs):
        self.args.update(kwargs)

    def __enter__(self):
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_value, tb):
        _trace.record_span('autotune.decision', 'autotune', self._wall0,
                           time.perf_counter() - self._t0, dict(self.args))
        return False


class DecisionLog(object):
    """Append-only JSONL decision log (one structured record per knob change;
    schema in ``docs/autotune.md``). Best-effort: an unwritable path degrades
    to in-memory decisions with one warning, never a failed pipeline."""

    def __init__(self, path):
        self.path = path
        self._warned = False

    def append(self, record):
        try:
            with open(self.path, 'a') as f:
                f.write(json.dumps(record) + '\n')
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning('autotune decision log %s unwritable (%s); '
                               'decisions stay in memory only', self.path, e)


class AutotuneConfig(object):
    """Bounds, cadence and hysteresis of the feedback controller.

    Every knob has an explicit ``[min, max]``; the controller can never move
    outside them (PT702 enforces the clamp statically, the clamp enforces it
    dynamically). ``None`` cooldowns derive from ``interval_s``.

    :param interval_s: evaluation cadence (also the history snapshot cadence)
    :param history_capacity: snapshots retained for windows/offline save
    :param stall_threshold: windowed ``reader_wait_fraction`` at/above which
        the window counts as stalled and the bottleneck knob may move
    :param low_water: windowed wait fraction at/below which the window counts
        as calm (a run of ``shrink_after_windows`` calm windows lets a grown
        worker slot retire)
    :param min_workers/max_workers: worker-pool bounds (``max_workers=None``
        defaults to ``min(2 * cpu_count, 16)`` at attach time)
    :param min_prefetch_bytes/max_prefetch_bytes: chunk-prefetch in-flight
        byte-budget bounds
    :param min_shuffle_capacity: floor for shuffle-buffer shrinks (growing
        re-uses the loader's configured capacity as the ceiling)
    :param cooldown_s: min seconds between moves of one knob (default
        ``2 * interval_s``)
    :param reverse_cooldown_s: min seconds before a knob may move in the
        OPPOSITE direction of its last move (default ``6 * interval_s``)
    :param freeze_s: knob freeze after two direction reversals (default
        ``20 * interval_s``)
    :param shrink_after_windows: consecutive calm windows before a worker
        slot retires
    :param shrink_workers: allow giving grown slots back (False = grow-only)
    :param decision_log: JSONL path for the structured decision log (None =
        in-memory ``Autotuner.decisions`` only)
    :param rollback: A/B-check every knob move — the first full evidence
        window after a move is compared against the move's own evidence
        window via :func:`observability.history.detect_regression`; on a
        detected regression the knob is reverted and frozen, recorded as a
        ``rollback`` decision
    :param rollback_throughput_ratio/rollback_stall_rise: the
        :func:`~petastorm_tpu.observability.history.detect_regression`
        thresholds the A/B check uses
    """

    def __init__(self, interval_s=2.0, history_capacity=_history.DEFAULT_CAPACITY,
                 stall_threshold=0.15, low_water=0.02,
                 min_workers=1, max_workers=None,
                 min_prefetch_bytes=8 << 20, max_prefetch_bytes=512 << 20,
                 min_shuffle_capacity=2,
                 cooldown_s=None, reverse_cooldown_s=None, freeze_s=None,
                 shrink_after_windows=5, shrink_workers=True,
                 decision_log=None, rollback=True,
                 rollback_throughput_ratio=0.7, rollback_stall_rise=0.15):
        if interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        if not 0.0 <= low_water < stall_threshold <= 1.0:
            raise ValueError('need 0 <= low_water < stall_threshold <= 1, got '
                             '{} / {}'.format(low_water, stall_threshold))
        if min_workers < 1:
            raise ValueError('min_workers must be >= 1')
        if max_workers is not None and max_workers < min_workers:
            raise ValueError('max_workers ({}) < min_workers ({})'.format(
                max_workers, min_workers))
        if min_prefetch_bytes > max_prefetch_bytes:
            raise ValueError('min_prefetch_bytes > max_prefetch_bytes')
        if shrink_after_windows < 1:
            raise ValueError('shrink_after_windows must be >= 1')
        if not 0.0 < rollback_throughput_ratio <= 1.0:
            raise ValueError('rollback_throughput_ratio must be in (0, 1]')
        if rollback_stall_rise < 0.0:
            raise ValueError('rollback_stall_rise must be >= 0')
        self.interval_s = interval_s
        self.history_capacity = history_capacity
        self.stall_threshold = stall_threshold
        self.low_water = low_water
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.min_prefetch_bytes = min_prefetch_bytes
        self.max_prefetch_bytes = max_prefetch_bytes
        self.min_shuffle_capacity = min_shuffle_capacity
        self.cooldown_s = cooldown_s if cooldown_s is not None else 2 * interval_s
        self.reverse_cooldown_s = (reverse_cooldown_s if reverse_cooldown_s is not None
                                   else 6 * interval_s)
        self.freeze_s = freeze_s if freeze_s is not None else 20 * interval_s
        self.shrink_after_windows = shrink_after_windows
        self.shrink_workers = shrink_workers
        self.decision_log = decision_log
        self.rollback = rollback
        self.rollback_throughput_ratio = rollback_throughput_ratio
        self.rollback_stall_rise = rollback_stall_rise

    def resolved_max_workers(self):
        if self.max_workers is not None:
            return self.max_workers
        return max(self.min_workers, min(2 * (os.cpu_count() or 1), 16))

    def __repr__(self):
        return ('AutotuneConfig(interval_s={}, stall_threshold={}, '
                'max_workers={}, decision_log={!r})'.format(
                    self.interval_s, self.stall_threshold,
                    self.max_workers, self.decision_log))


def resolve_autotune(autotune):
    """Normalize the ``make_reader`` kwarg: falsy -> None (off), ``True`` ->
    defaults, an :class:`AutotuneConfig` -> itself."""
    if not autotune:
        return None
    if autotune is True:
        return AutotuneConfig()
    if isinstance(autotune, AutotuneConfig):
        return autotune
    raise ValueError('autotune must be False/None, True, or an AutotuneConfig, '
                     'got {!r}'.format(autotune))


class _KnobState(object):
    """Per-knob hysteresis bookkeeping."""

    __slots__ = ('last_t', 'last_direction', 'reversals', 'frozen_until')

    def __init__(self):
        self.last_t = None
        self.last_direction = None
        self.reversals = 0
        self.frozen_until = 0.0


class Autotuner(object):
    """The closed loop: owns a :class:`HistoryRecorder` over the reader (or,
    once attached, the loader) diagnostics and a control thread ticking every
    ``config.interval_s``. All targets are duck-typed so the offline replay
    (``petastorm_tpu.autotune.cli``) can drive the identical decision path
    against simulated knobs:

    :param pool: needs ``workers_count`` and (for the knob to be live)
        ``add_worker_slot``/``retire_worker_slot``
    :param chunk_cache: a :class:`~petastorm_tpu.chunkstore.ChunkCacheConfig`
        (or anything with ``prefetch_budget_bytes`` + ``set_prefetch_budget``)
    :param ventilator: optional; its in-flight budget follows pool growth
    :param diagnostics_fn: evidence source (``Reader.diagnostics`` by default;
        :meth:`attach_loader` rebinds it to the loader, which adds the
        consumer-side ``reader_wait_*`` signal)
    """

    def __init__(self, config, pool=None, chunk_cache=None, ventilator=None,
                 diagnostics_fn=None, loader=None):
        self.config = config
        self._pool = pool
        self._chunk_cache = chunk_cache
        self._ventilator = ventilator
        self._loader = loader
        self._diagnostics_fn = diagnostics_fn
        self.history = _history.HistoryRecorder(
            self._diagnostics, interval_s=config.interval_s,
            capacity=config.history_capacity)
        self.decisions = []
        self._decisions_lock = threading.Lock()
        self._log = DecisionLog(config.decision_log) if config.decision_log else None
        self._knobs = {}
        self._calm_windows = 0
        self._grown_slots = 0  # net slots this controller added (shrink floor)
        self._pending_ab = None  # last knob move awaiting its A/B window
        self._stop_event = threading.Event()
        self._thread = None

    # -- wiring --------------------------------------------------------------

    def _diagnostics(self):
        if self._loader is not None:
            return self._loader.diagnostics
        if self._diagnostics_fn is not None:
            return self._diagnostics_fn()
        return {}

    def attach_loader(self, loader):
        """Called by :class:`~petastorm_tpu.jax.loader.JaxDataLoader` when it
        wraps an autotuned reader: the loader's diagnostics carry the
        consumer-side wait signal, and its shuffle buffer becomes tunable."""
        self._loader = loader

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Autotuner already started')
        self._stop_event.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name='pstpu-autotune')
        self._thread.start()
        return self

    def _loop(self):
        self.history.record_now()
        while not self._stop_event.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the tuner is advisory: a decision error must never kill the pipeline
                logger.warning('autotune tick failed: %s', e)

    def stop(self):
        self._stop_event.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
            self._thread = None

    def join(self):
        self.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        return False

    # -- the loop body -------------------------------------------------------

    def tick(self, now=None):
        """One control evaluation: snapshot, window, decide, act. Returns the
        decision record (or None). Public so tests and the offline replay can
        drive the loop without the thread."""
        self.history.record_now()
        window = self.history.window_last()
        if window is None or window['window_s'] < 0.25 * self.config.interval_s:
            return None
        return self.evaluate(window, now=now)

    def evaluate(self, window, now=None):
        """Pure-ish decision step over one evidence window (actuation happens
        through the attached knob targets)."""
        now = now if now is not None else time.monotonic()
        report = _history.windowed_stall_report(window)
        # A/B check first: the window that just closed is the evidence for the
        # PREVIOUS move — a detected regression reverts + freezes that knob
        # before any new move is considered
        if self._pending_ab is not None:
            pending, self._pending_ab = self._pending_ab, None
            if self.config.rollback:
                regression = _history.detect_regression(
                    pending['window'], window,
                    throughput_ratio=self.config.rollback_throughput_ratio,
                    stall_rise=self.config.rollback_stall_rise)
                if regression is not None:
                    record = self._rollback(pending, regression, report,
                                            window, now)
                    if record is not None:
                        return record
        wait_frac = report.get('reader_wait_fraction') or 0.0
        if wait_frac >= self.config.stall_threshold:
            self._calm_windows = 0
            return self._on_stalled(report, window, now)
        if wait_frac <= self.config.low_water:
            self._calm_windows += 1
            if (self.config.shrink_workers
                    and self._calm_windows >= self.config.shrink_after_windows):
                self._calm_windows = 0
                return self._shrink_workers(report, window, now)
        else:
            self._calm_windows = 0
        return None

    def _on_stalled(self, report, window, now):
        bottleneck = report.get('bottleneck')
        if bottleneck == 'worker.chunk_fetch':
            decision = self._raise_prefetch(report, window, now)
            if decision is not None:
                return decision
            return self._grow_workers(report, window, now)
        if bottleneck in _WORKER_BOTTLENECKS:
            return self._grow_workers(report, window, now)
        if bottleneck == 'consumer.assembly':
            return self._shrink_shuffle(report, window, now)
        return None

    # -- hysteresis ----------------------------------------------------------

    def _knob_state(self, name):
        state = self._knobs.get(name)
        if state is None:
            state = self._knobs[name] = _KnobState()
        return state

    def _allow(self, name, direction, now):
        """The oscillation guard: cooldown, reverse-cooldown, reversal freeze."""
        cfg = self.config
        state = self._knob_state(name)
        if now < state.frozen_until:
            return False
        if state.last_t is not None and now - state.last_t < cfg.cooldown_s:
            return False
        if state.last_direction is not None and direction != state.last_direction:
            if now - state.last_t < cfg.reverse_cooldown_s:
                return False
            state.reversals += 1
            if state.reversals >= 2:
                state.frozen_until = now + cfg.freeze_s
                state.reversals = 0
                logger.warning('autotune: knob %r reversed direction twice; '
                               'frozen for %.1fs (oscillation guard)',
                               name, cfg.freeze_s)
                return False
        return True

    def _mark(self, name, direction, now):
        state = self._knob_state(name)
        state.last_t = now
        state.last_direction = direction

    # -- actions -------------------------------------------------------------
    # Every actuator call in this package must sit inside a decision_span and
    # take a clamp()-ed target (lint rule PT702): the span + log record make
    # each change explainable, the clamp makes the bounds unbreakable.

    def _record(self, knob, action, before, after, reason, report, window,
                clamped, regression=None):
        record = {
            'ts': round(time.time(), 3),
            'knob': knob, 'action': action,
            'from': before, 'to': after, 'clamped': bool(clamped),
            'reason': reason,
            'window': {
                'span_s': window.get('window_s'),
                'reader_wait_fraction': report.get('reader_wait_fraction'),
                'wait_proxy': report.get('wait_proxy'),
                'bottleneck': report.get('bottleneck'),
                'rows_per_s': window.get('rows_per_s'),
                'stages': report.get('stages'),
            },
        }
        if regression is not None:
            record['regression'] = regression
        if action != 'rollback':
            # arm the A/B check: the NEXT full window is this move's verdict
            # (a rollback is the verdict itself — it never re-arms)
            self._pending_ab = {'record': record, 'window': window}
        with self._decisions_lock:
            self.decisions.append(record)
            if len(self.decisions) > 1000:
                del self.decisions[:-1000]
        if self._log is not None:
            self._log.append(record)
        obs.count('autotune_decisions_total')
        logger.info('autotune: %s %s %s -> %s (%s)', action, knob, before,
                    after, reason)
        return record

    def _grow_workers(self, report, window, now):
        pool = self._pool
        if pool is None or not hasattr(pool, 'add_worker_slot'):
            return None
        before = pool.workers_count
        hi = self.config.resolved_max_workers()
        target = clamp(before + 1, self.config.min_workers, hi)
        if target <= before or not self._allow('workers', 'grow', now):
            return None
        reason = 'bottleneck {} at {:.0%} of windowed wait'.format(
            report.get('bottleneck'), self._bottleneck_share(report))
        with decision_span(knob='workers', action='grow', before=before,
                           target=target, reason=reason) as span:
            pool.add_worker_slot()
            after = pool.workers_count
            span.note(after=after)
            if self._ventilator is not None \
                    and hasattr(self._ventilator, 'set_max_queue_size'):
                # the in-flight budget tracks the pool size, as at construction
                self._ventilator.set_max_queue_size(after + 2)
        self._mark('workers', 'grow', now)
        self._grown_slots += 1
        return self._record('workers', 'grow', before, after, reason, report,
                            window, clamped=target != before + 1)

    def _shrink_workers(self, report, window, now):
        pool = self._pool
        if pool is None or not hasattr(pool, 'retire_worker_slot'):
            return None
        before = pool.workers_count
        if self._grown_slots <= 0:
            return None  # never shrink below what the user configured
        target = clamp(before - 1, self.config.min_workers, None)
        if target >= before or not self._allow('workers', 'shrink', now):
            return None
        reason = 'calm pipeline ({} consecutive windows <= {:.0%} wait)'.format(
            self.config.shrink_after_windows, self.config.low_water)
        with decision_span(knob='workers', action='shrink', before=before,
                           target=target, reason=reason) as span:
            pool.retire_worker_slot()
            after = pool.workers_count
            span.note(after=after)
            if self._ventilator is not None \
                    and hasattr(self._ventilator, 'set_max_queue_size'):
                self._ventilator.set_max_queue_size(after + 2)
        if after >= before:
            return None  # every slot was busy: the pool declined this tick
        self._mark('workers', 'shrink', now)
        self._grown_slots -= 1
        return self._record('workers', 'shrink', before, after, reason, report,
                            window, clamped=target != before - 1)

    def _raise_prefetch(self, report, window, now):
        cache = self._chunk_cache
        if cache is None or not hasattr(cache, 'set_prefetch_budget'):
            return None
        before = cache.prefetch_budget_bytes
        target = clamp(before * 2, self.config.min_prefetch_bytes,
                       self.config.max_prefetch_bytes)
        if target <= before or not self._allow('prefetch_bytes', 'grow', now):
            return None
        reason = ('chunk-fetch bound: raising the prefetch in-flight byte '
                  'budget to overlap fetches with decode')
        with decision_span(knob='prefetch_bytes', action='grow', before=before,
                           target=target, reason=reason):
            cache.set_prefetch_budget(target)
        self._mark('prefetch_bytes', 'grow', now)
        return self._record('prefetch_bytes', 'grow', before, target, reason,
                            report, window, clamped=target != before * 2)

    def _shrink_shuffle(self, report, window, now):
        loader = self._loader
        if loader is None or not hasattr(loader, 'set_shuffle_capacity'):
            return None
        before = getattr(loader, 'shuffle_capacity', 0)
        if before <= 0:
            return None  # no shuffling buffer in play
        target = clamp(before // 2, self.config.min_shuffle_capacity, None)
        if target >= before or not self._allow('shuffle_capacity', 'shrink', now):
            return None
        reason = ('consumer-side assembly bound: shrinking the shuffle buffer '
                  'reduces per-emit gather work')
        with decision_span(knob='shuffle_capacity', action='shrink',
                           before=before, target=target, reason=reason):
            loader.set_shuffle_capacity(target)
        self._mark('shuffle_capacity', 'shrink', now)
        return self._record('shuffle_capacity', 'shrink', before, target,
                            reason, report, window,
                            clamped=target != before // 2)

    def _rollback(self, pending, regression, report, window, now):
        """Revert the knob move in ``pending`` (its A/B window regressed) and
        freeze the knob so the controller does not immediately retry the move
        it just proved harmful. Recorded as a ``rollback`` decision carrying
        the regression evidence (ROADMAP follow-up: autotune regression
        rollback)."""
        rec = pending['record']
        knob, moved = rec['knob'], rec['action']
        reason = 'regression after {} {} ({}): reverting to {}'.format(
            moved, knob, regression.get('kind'), rec['from'])
        before = after = None
        if knob == 'workers':
            pool = self._pool
            if pool is None:
                return None
            before = pool.workers_count
            with decision_span(knob=knob, action='rollback', before=before,
                               target=rec['from'], reason=reason) as span:
                if moved == 'grow' and hasattr(pool, 'retire_worker_slot') \
                        and before > rec['from']:
                    pool.retire_worker_slot()
                    self._grown_slots = max(0, self._grown_slots - 1)
                elif moved == 'shrink' and hasattr(pool, 'add_worker_slot') \
                        and before < rec['from']:
                    pool.add_worker_slot()
                    self._grown_slots += 1
                after = pool.workers_count
                span.note(after=after)
                if self._ventilator is not None \
                        and hasattr(self._ventilator, 'set_max_queue_size'):
                    self._ventilator.set_max_queue_size(after + 2)
        elif knob == 'prefetch_bytes':
            cache = self._chunk_cache
            if cache is None or not hasattr(cache, 'set_prefetch_budget'):
                return None
            before = cache.prefetch_budget_bytes
            target = clamp(rec['from'], self.config.min_prefetch_bytes,
                           self.config.max_prefetch_bytes)
            with decision_span(knob=knob, action='rollback', before=before,
                               target=target, reason=reason):
                cache.set_prefetch_budget(target)
            after = target
        elif knob == 'shuffle_capacity':
            loader = self._loader
            if loader is None or not hasattr(loader, 'set_shuffle_capacity'):
                return None
            before = getattr(loader, 'shuffle_capacity', 0)
            target = clamp(rec['from'], self.config.min_shuffle_capacity, None)
            with decision_span(knob=knob, action='rollback', before=before,
                               target=target, reason=reason):
                loader.set_shuffle_capacity(target)
            after = target
        else:
            return None
        if after == before:
            return None  # nothing to revert (pool declined / already there)
        state = self._knob_state(knob)
        state.last_t = now
        state.last_direction = None  # the reverted move does not count
        state.frozen_until = now + self.config.freeze_s
        logger.warning('autotune: %s move of %r regressed (%s); reverted and '
                       'frozen for %.1fs', moved, knob, regression.get('kind'),
                       self.config.freeze_s)
        return self._record(knob, 'rollback', before, after, reason, report,
                            window, clamped=False, regression=regression)

    @staticmethod
    def _bottleneck_share(report):
        stages = report.get('stages') or {}
        bottleneck = report.get('bottleneck')
        total = sum(stages.values())
        if not total or bottleneck not in stages:
            return 0.0
        return stages[bottleneck] / total

    # -- surfaces ------------------------------------------------------------

    def decision_records(self):
        with self._decisions_lock:
            return list(self.decisions)

    def proposal(self):
        """Current knob values as a config proposal (the offline replay's
        output; live tuners report the values they steered to)."""
        out = {}
        if self._pool is not None and hasattr(self._pool, 'workers_count'):
            out['workers_count'] = self._pool.workers_count
        if self._chunk_cache is not None \
                and hasattr(self._chunk_cache, 'prefetch_budget_bytes'):
            out['prefetch_budget_bytes'] = self._chunk_cache.prefetch_budget_bytes
        if self._loader is not None and hasattr(self._loader, 'shuffle_capacity'):
            out['shuffling_queue_capacity'] = self._loader.shuffle_capacity
        return out
