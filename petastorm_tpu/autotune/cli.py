"""``petastorm-tpu-autotune``: offline replay — propose a config from a
recorded run, without running the pipeline.

Feed it a telemetry history (recorded by ``HistoryRecorder.save``, a
``JsonlExporter`` file, or ``petastorm-tpu-diagnose --watch --json`` output —
any JSONL of ``{"ts", "diag"|"metrics"}`` lines) or a Chrome trace JSON
(``--trace``, e.g. from ``bench.py --trace-out``)::

    petastorm-tpu-autotune history.jsonl --workers 3
    petastorm-tpu-autotune --trace pipeline.json --json

The recorded run's windows replay through the **identical**
:class:`~petastorm_tpu.autotune.controller.Autotuner` decision path the live
controller runs — same bottleneck rules, same hysteresis, same clamps — but
against simulated knobs, so the output is the decision trajectory plus the
final proposed ``make_reader`` settings. See ``docs/autotune.md``.
"""

from __future__ import annotations

import argparse
import json
import sys

from petastorm_tpu.autotune.controller import AutotuneConfig, Autotuner
from petastorm_tpu.observability import history as _history

#: trace span names folded into each synthesized window's stage seconds
_TRACE_STAGES = ('pool_wait', 'read', 'chunk_fetch', 'fused_decode', 'decode',
                 'transform', 'collate', 'ventilate')


class _SimPool(object):
    """Simulated worker pool: counts slots, never spawns anything."""

    def __init__(self, workers_count):
        self.workers_count = workers_count

    def add_worker_slot(self):
        self.workers_count += 1
        return self.workers_count

    def retire_worker_slot(self):
        if self.workers_count > 1:
            self.workers_count -= 1
        return self.workers_count


class _SimChunkCache(object):
    """Simulated chunk-cache config: just the prefetch budget."""

    def __init__(self, prefetch_budget_bytes):
        self.prefetch_budget_bytes = prefetch_budget_bytes

    def set_prefetch_budget(self, n):
        self.prefetch_budget_bytes = int(n)


class _SimLoader(object):
    """Simulated loader: just the shuffle-buffer capacity knob."""

    def __init__(self, shuffle_capacity):
        self.shuffle_capacity = shuffle_capacity
        self.diagnostics = {}

    def set_shuffle_capacity(self, capacity):
        self.shuffle_capacity = int(capacity)


def windows_from_trace(path, interval_s=2.0):
    """Synthesize evidence windows from a Chrome trace: complete ('X') stage
    events bucket by wall time into ``interval_s`` windows; each window's
    ``stage_<name>_s`` is the sum of that stage's durations in the bucket.
    ``pool_wait`` doubles as the wait signal (``wait_proxy='pool_wait'``) —
    traces carry no loader wait counter."""
    with open(path) as f:
        doc = json.load(f)
    events = doc.get('traceEvents', []) if isinstance(doc, dict) else []
    stamped = [e for e in events
               if isinstance(e, dict) and e.get('ph') == 'X'
               and e.get('name') in _TRACE_STAGES and 'ts' in e]
    if not stamped:
        return []
    t0 = min(e['ts'] for e in stamped)
    buckets = {}
    for e in stamped:
        idx = int((e['ts'] - t0) / (interval_s * 1e6))
        bucket = buckets.setdefault(idx, {})
        key = 'stage_{}_s'.format(e['name'])
        bucket[key] = bucket.get(key, 0.0) + e.get('dur', 0) / 1e6
    windows = []
    for idx in sorted(buckets):
        win = dict(buckets[idx])
        wait = win.get('stage_pool_wait_s', 0.0)
        win['window_s'] = interval_s
        win['reader_wait_s'] = round(wait, 4)
        win['reader_wait_fraction'] = round(min(wait / interval_s, 1.0), 4)
        win['wait_proxy'] = 'pool_wait'
        win['rows_per_s'] = None
        windows.append(win)
    return windows


def replay(windows, config=None, workers=3, prefetch_bytes=64 << 20,
           shuffle_capacity=0):
    """Run the evidence windows through a dry Autotuner against simulated
    knobs. Returns ``(proposal_dict, decision_records, tuner)``."""
    config = config or AutotuneConfig()
    pool = _SimPool(workers)
    cache = _SimChunkCache(prefetch_bytes)
    loader = _SimLoader(shuffle_capacity) if shuffle_capacity > 0 else None
    tuner = Autotuner(config, pool=pool, chunk_cache=cache, loader=loader)
    now = 0.0
    for window in windows:
        now += float(window.get('window_s') or config.interval_s)
        tuner.evaluate(window, now=now)
    proposal = tuner.proposal()
    proposal.setdefault('prefetch_budget_bytes', cache.prefetch_budget_bytes)
    return proposal, tuner.decision_records(), tuner


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-autotune',
        description='Replay a recorded telemetry history (or Chrome trace) '
                    'through the autotune decision path and propose a config '
                    'without running the pipeline.')
    parser.add_argument('history', nargs='?', default=None,
                        help='JSONL history file (HistoryRecorder.save / '
                             'JsonlExporter / diagnose --watch --json output)')
    parser.add_argument('--trace', default=None,
                        help='Chrome trace JSON instead of a history file')
    parser.add_argument('--interval-s', type=float, default=2.0,
                        help='evaluation window for --trace bucketing and the '
                             'replayed controller cadence')
    parser.add_argument('--workers', type=int, default=3,
                        help='workers_count the recorded run used')
    parser.add_argument('--prefetch-bytes', type=int, default=64 << 20,
                        help='prefetch in-flight byte budget the run used')
    parser.add_argument('--shuffle-capacity', type=int, default=0,
                        help='shuffling_queue_capacity the run used (0 = none)')
    parser.add_argument('--max-workers', type=int, default=None)
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='print the proposal as JSON')
    args = parser.parse_args(argv)

    if (args.history is None) == (args.trace is None):
        parser.error('give exactly one of: a history JSONL file, or --trace')
    if args.trace is not None:
        windows = windows_from_trace(args.trace, interval_s=args.interval_s)
    else:
        snaps = _history.load_history(args.history)
        windows = _history.history_windows(snaps)
    if not windows:
        print('no usable evidence windows in the input (need >= 2 history '
              'snapshots, or a trace with stage spans)', file=sys.stderr)
        return 1

    config = AutotuneConfig(interval_s=args.interval_s,
                            max_workers=args.max_workers)
    proposal, decisions, _tuner = replay(
        windows, config=config, workers=args.workers,
        prefetch_bytes=args.prefetch_bytes,
        shuffle_capacity=args.shuffle_capacity)

    if args.as_json:
        print(json.dumps({'windows': len(windows), 'proposal': proposal,
                          'decisions': decisions}))
        return 0
    print('replayed {} evidence window(s)'.format(len(windows)))
    if decisions:
        print('decision trajectory:')
        for d in decisions:
            print('  [{}] {} {}: {} -> {}  ({})'.format(
                d['ts'], d['action'], d['knob'], d['from'], d['to'],
                d['reason']))
    else:
        print('no knob changes proposed (no stalled window crossed the '
              'threshold, or hysteresis held every move)')
    print('proposed configuration:')
    for key in sorted(proposal):
        print('  {} = {}'.format(key, proposal[key]))
    print('apply with make_reader(..., workers_count={}) and the knobs above; '
          'see docs/autotune.md'.format(proposal.get('workers_count', '?')))
    return 0


if __name__ == '__main__':
    sys.exit(main())
