"""Closed-loop autotuning: the stall report turns the knobs itself.

PR 3 gave the pipeline eyes — per-stage metrics and an input-stall report
that names the bottleneck — but a human still read the report and re-ran
with different ``workers_count`` / prefetch / shuffle settings. This package
closes the loop:

* :class:`~petastorm_tpu.autotune.controller.Autotuner` — a feedback
  controller that watches **windowed** telemetry history
  (``observability/history.py``) and adjusts, at runtime: the supervised
  worker pool (grow a fresh slot / retire an idle one through the existing
  supervision machinery), the chunk-store prefetch in-flight byte budget,
  and the loader's shuffle-buffer capacity;
* :class:`~petastorm_tpu.autotune.controller.AutotuneConfig` — explicit
  per-knob ``[min, max]`` bounds, cadence, and the hysteresis stack
  (cooldown / reverse-cooldown / reversal freeze) that keeps alternating
  bottlenecks from thrashing a knob;
* every change is **explainable**: an ``autotune.decision`` span in the
  trace ring plus a structured JSONL decision-log record carrying the
  evidence window (lint rule PT702 statically rejects an unwrapped or
  unclamped knob write in this package);
* ``petastorm-tpu-autotune`` (:mod:`petastorm_tpu.autotune.cli`) — offline
  mode: replay a recorded history (or Chrome trace) through the identical
  decision path against simulated knobs and print a proposed config without
  running the pipeline.

Enable with ``make_reader(..., autotune=True)`` (or an
:class:`AutotuneConfig`); ``JaxDataLoader`` attaches itself automatically so
the controller sees the consumer-side wait signal. The default is OFF and
costs nothing: no recorder, no thread, no snapshots. See ``docs/autotune.md``.
"""

from __future__ import annotations

from petastorm_tpu.autotune.controller import (AutotuneConfig, Autotuner,  # noqa: F401
                                               DecisionLog, clamp, decision_span,
                                               resolve_autotune)

__all__ = ['AutotuneConfig', 'Autotuner', 'DecisionLog', 'clamp',
           'decision_span', 'resolve_autotune']
