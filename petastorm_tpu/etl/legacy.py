"""Interop: read datasets written by the ORIGINAL petastorm library.

The reference stores its Unischema pickled into ``_common_metadata`` under
``dataset-toolkit.unischema.v1`` (reference etl/dataset_metadata.py:34-35), with
per-file row-group counts and row-group indexes under sibling keys, and its
``etl/legacy.py:22-47`` binary-patches even older package names inside the
pickle stream. A user migrating from petastorm must be able to point
``make_reader`` at an existing dataset — the row payload formats are already
compatible (np.save / npz / png / typed scalars match our codecs byte-for-byte).

This module decodes those pickles WITHOUT petastorm or pyspark installed and
WITHOUT arbitrary code execution: a restricted unpickler maps the reference's
class names (including its own legacy aliases) onto local shims, and anything
outside the allowlist raises. The shims are then converted to petastorm_tpu
schema/codec/indexer objects.
"""

from __future__ import annotations

import io
import logging
import pickle
from collections import OrderedDict
from decimal import Decimal

import numpy as np

logger = logging.getLogger(__name__)

#: metadata keys the reference writes (etl/dataset_metadata.py:34-35,
#: etl/rowgroup_indexing.py:33)
REF_UNISCHEMA_KEY = b'dataset-toolkit.unischema.v1'
REF_ROW_GROUPS_PER_FILE_KEY = b'dataset-toolkit.num_row_groups_per_file.v1'
REF_ROW_GROUP_INDEX_KEY = b'dataset-toolkit.rowgroups_index.v1'

#: package aliases the reference itself migrates between (legacy.py:31)
_SCHEMA_MODULES = ('petastorm', 'dataset_toolkit',
                   'av.experimental.deepdrive.dataset_toolkit', 'av.ml.dataset_toolkit')


class _Shim(object):
    """Instance reconstructed from a foreign pickle: plain attribute bag.
    Tolerates every pickle reconstruction path (NEWOBJ with or without args,
    copyreg._reconstructor, BUILD with a state dict)."""

    def __new__(cls, *args, **kwargs):
        obj = object.__new__(cls)
        obj._ctor_args = args
        obj._ctor_kwargs = kwargs
        return obj

    def __init__(self, *args, **kwargs):
        pass


class _RefUnischema(_Shim):
    pass


class _RefUnischemaField(tuple):
    """Reference UnischemaField is a namedtuple (name, numpy_dtype, shape,
    codec, nullable): a tuple subclass survives both NEWOBJ (protocol >=2 via
    __getnewargs__) and copyreg._reconstructor(cls, tuple, values)
    (protocols 0/1) reconstruction."""

    def __new__(cls, *args):
        # NEWOBJ passes the 5 fields as positional args; _reconstructor passes
        # one tuple containing them
        if len(args) == 1 and isinstance(args[0], tuple):
            args = args[0]
        return tuple.__new__(cls, args)

    @property
    def _ctor_args(self):
        return tuple(self)


class _RefScalarCodec(_Shim):
    pass


class _RefNdarrayCodec(_Shim):
    pass


class _RefCompressedNdarrayCodec(_Shim):
    pass


class _RefCompressedImageCodec(_Shim):
    pass


class _RefSingleFieldIndexer(_Shim):
    pass


class _RefFieldNotNullIndexer(_Shim):
    pass


class _SparkTypeStub(_Shim):
    """Stands in for any pyspark.sql.types.* instance (pyspark need not be
    installed). The class name is what conversion logic looks at."""
    spark_type_name = None


_CODEC_SHIMS = {
    'ScalarCodec': _RefScalarCodec,
    'NdarrayCodec': _RefNdarrayCodec,
    'CompressedNdarrayCodec': _RefCompressedNdarrayCodec,
    'CompressedImageCodec': _RefCompressedImageCodec,
}

_NUMPY_ALLOWED = {
    'bool_', 'int8', 'int16', 'int32', 'int64', 'uint8', 'uint16', 'uint32',
    'uint64', 'float16', 'float32', 'float64', 'str_', 'unicode_', 'bytes_',
    'string_', 'object_', 'datetime64', 'timedelta64', 'dtype', 'ndarray',
}

_spark_type_stubs = {}


def _spark_type_stub(name):
    if name not in _spark_type_stubs:
        _spark_type_stubs[name] = type(name, (_SparkTypeStub,), {'spark_type_name': name})
    return _spark_type_stubs[name]


class _RestrictedUnpickler(pickle.Unpickler):
    """Only reference schema/codec/indexer classes, pyspark type names, numpy
    scalar types, and basic containers may appear in the stream."""

    def find_class(self, module, name):
        for pkg in _SCHEMA_MODULES:
            if module == pkg + '.unischema' or module == pkg + '.sequence':
                if name == 'Unischema':
                    return _RefUnischema
                if name == 'UnischemaField':
                    return _RefUnischemaField
            if module == pkg + '.codecs' and name in _CODEC_SHIMS:
                return _CODEC_SHIMS[name]
            if module in (pkg + '.etl.rowgroup_indexers', pkg + '.rowgroup_indexers'):
                if name == 'SingleFieldIndexer':
                    return _RefSingleFieldIndexer
                if name == 'FieldNotNullIndexer':
                    return _RefFieldNotNullIndexer
        if module == 'pyspark.sql.types':
            return _spark_type_stub(name)
        if module == 'numpy' and name in _NUMPY_ALLOWED:
            return getattr(np, name)
        if module in ('numpy.core.multiarray', 'numpy._core.multiarray') and \
                name in ('scalar', '_reconstruct'):
            import importlib
            try:
                ma = importlib.import_module('numpy._core.multiarray')
            except ImportError:
                ma = importlib.import_module('numpy.core.multiarray')
            return getattr(ma, name)
        if module == 'collections' and name in ('OrderedDict', 'defaultdict'):
            import collections
            return getattr(collections, name)
        if module == 'decimal' and name == 'Decimal':
            return Decimal
        if module in ('copy_reg', 'copyreg') and name == '_reconstructor':
            import copyreg
            return copyreg._reconstructor
        if module in ('__builtin__', 'builtins') and name in ('object', 'set', 'frozenset'):
            return {'object': object, 'set': set, 'frozenset': frozenset}[name]
        raise pickle.UnpicklingError(
            'Refusing to depickle {}.{} from legacy petastorm metadata'.format(module, name))


def restricted_loads(data):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


# -- shim -> petastorm_tpu conversion ------------------------------------------

def _convert_codec(shim, field_shape):
    from petastorm_tpu import codecs

    if shim is None:
        return None
    state = shim.__dict__
    if isinstance(shim, _RefScalarCodec):
        return codecs.ScalarCodec()
    if isinstance(shim, _RefNdarrayCodec):
        return codecs.NdarrayCodec()
    if isinstance(shim, _RefCompressedNdarrayCodec):
        return codecs.CompressedNdarrayCodec()
    if isinstance(shim, _RefCompressedImageCodec):
        # reference stores '.png'/'.jpeg' with the leading dot (codecs.py:62)
        fmt = state.get('_image_codec', '.png').lstrip('.')
        return codecs.CompressedImageCodec(fmt, quality=state.get('_quality', 80))
    raise pickle.UnpicklingError('Unknown legacy codec shim {!r}'.format(shim))


def _convert_field(shim):
    from petastorm_tpu.unischema import UnischemaField

    name, numpy_dtype, shape, codec, nullable = (tuple(shim._ctor_args) + (None, False))[:5]
    return UnischemaField(name, numpy_dtype, shape,
                          codec=_convert_codec(codec, shape), nullable=nullable)


def convert_unischema(shim):
    """Reference Unischema shim -> :class:`petastorm_tpu.unischema.Unischema`."""
    from petastorm_tpu.unischema import Unischema

    state = shim.__dict__
    fields = [f for f in state.get('_fields', {}).values()
              if isinstance(f, _RefUnischemaField)]
    return Unischema(state.get('_name', 'legacy'), [_convert_field(f) for f in fields])


def load_legacy_unischema(pickled):
    """Pickle bytes from ``dataset-toolkit.unischema.v1`` -> our Unischema."""
    shim = restricted_loads(pickled)
    if not isinstance(shim, _RefUnischema):
        raise pickle.UnpicklingError(
            'legacy unischema metadata did not contain a Unischema (got {!r})'.format(type(shim)))
    schema = convert_unischema(shim)
    logger.info('Loaded legacy petastorm unischema %r (%d fields)', schema.name, len(schema.fields))
    return schema


def load_legacy_row_group_counts(raw):
    """Bytes from ``dataset-toolkit.num_row_groups_per_file.v1`` -> dict of
    relative file path -> row-group count. Unlike the schema/index keys this
    one is JSON in the reference (etl/dataset_metadata.py:226-228)."""
    import json

    counts = json.loads(raw.decode('utf-8'))
    if not isinstance(counts, dict):
        raise ValueError('legacy row-group counts were not a dict')
    return {str(k): int(v) for k, v in counts.items()}


def load_legacy_rowgroup_indexes(pickled):
    """Pickle bytes from ``dataset-toolkit.rowgroups_index.v1`` -> dict of
    index name -> petastorm_tpu indexer."""
    from petastorm_tpu.etl.rowgroup_indexers import FieldNotNullIndexer, SingleFieldIndexer

    raw = restricted_loads(pickled)
    if not isinstance(raw, dict):
        raise pickle.UnpicklingError('legacy rowgroup index metadata was not a dict')
    from petastorm_tpu.etl.rowgroup_indexers import _json_key

    out = {}
    for name, shim in raw.items():
        state = getattr(shim, '__dict__', {})
        index_name = state.get('_index_name', name)
        column = state.get('_column_name')
        if isinstance(shim, _RefSingleFieldIndexer):
            # reference keys values natively; ours uses JSON-stable string keys
            data = {_json_key(k): set(v) for k, v in state.get('_index_data', {}).items()}
            out[name] = SingleFieldIndexer(index_name, column, index_dict=data)
        elif isinstance(shim, _RefFieldNotNullIndexer):
            # reference _index_data is a plain set of piece indexes
            out[name] = FieldNotNullIndexer(index_name, column,
                                            piece_indexes=set(state.get('_index_data', ())))
        else:
            raise pickle.UnpicklingError('Unknown legacy indexer type {!r}'.format(type(shim)))
    return out
