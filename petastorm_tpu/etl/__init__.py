"""ETL: dataset materialization, metadata, and row-group indexing.

Parity: /root/reference/petastorm/etl/ — minus the Spark dependency. Datasets are
written by a local pyarrow-backed writer (optionally parallelized over a worker
pool); metadata lives as JSON strings in the Parquet ``_common_metadata``
key-value footer instead of pickles.
"""

from petastorm_tpu.etl.dataset_metadata import (  # noqa: F401
    materialize_dataset, write_petastorm_dataset, DatasetWriter,
    get_schema, get_schema_from_dataset_url, infer_or_load_unischema,
    load_row_groups, RowGroupPiece, PetastormMetadataError,
)
from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index, get_row_group_indexes  # noqa: F401
from petastorm_tpu.etl.rowgroup_indexers import SingleFieldIndexer, FieldNotNullIndexer  # noqa: F401
from petastorm_tpu.etl.indexer_base import RowGroupIndexerBase  # noqa: F401
