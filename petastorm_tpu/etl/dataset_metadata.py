"""Dataset materialization and metadata.

Parity with the reference (/root/reference/petastorm/etl/dataset_metadata.py):
  * ``materialize_dataset`` context manager (:52) — here it brackets a local
    pyarrow-backed :class:`DatasetWriter` instead of a Spark write.
  * unischema metadata key (:34-35) — stored as JSON, not pickle.
  * per-file row-group counts key (:195-228).
  * ``load_row_groups`` three-way fallback (:231-336): custom key ->
    ``_metadata`` summary file -> parallel footer reads.
  * ``get_schema`` / ``get_schema_from_dataset_url`` / ``infer_or_load_unischema``
    (:339-397).

TPU-first notes: the writer controls row-group byte size directly (row groups are
the unit of parallel decode AND of shard assignment across pod hosts, so their
sizing determines load balance); all metadata is language-neutral JSON.
"""

from __future__ import annotations

import json
import logging
import os
import posixpath
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager

import numpy as np
import pyarrow as pa
import pyarrow.fs as pafs
import pyarrow.parquet as pq

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.fs import FilesystemResolver
from petastorm_tpu.unischema import Unischema, encode_row

logger = logging.getLogger(__name__)

UNISCHEMA_KEY = b'petastorm_tpu.unischema.v1'
ROW_GROUPS_PER_FILE_KEY = b'petastorm_tpu.num_row_groups_per_file.v1'
ROW_GROUP_INDEX_KEY = b'petastorm_tpu.rowgroups_index.v1'

_COMMON_METADATA = '_common_metadata'
_SUMMARY_METADATA = '_metadata'

DEFAULT_ROW_GROUP_SIZE_MB = 32


class PetastormMetadataError(PetastormTpuError):
    """Dataset metadata is missing or malformed."""


class RowGroupPiece(object):
    """One row group of one Parquet file — the unit of work ventilated to decode
    workers and the unit of shard assignment across hosts."""

    __slots__ = ('path', 'row_group', 'num_rows', 'partition_keys')

    def __init__(self, path, row_group, num_rows=None, partition_keys=None):
        self.path = path
        self.row_group = row_group
        self.num_rows = num_rows
        self.partition_keys = partition_keys or {}

    def __repr__(self):
        return 'RowGroupPiece({!r}, rg={}, rows={}, partitions={})'.format(
            self.path, self.row_group, self.num_rows, self.partition_keys)

    def __eq__(self, other):
        return (isinstance(other, RowGroupPiece) and self.path == other.path and
                self.row_group == other.row_group)

    def __hash__(self):
        return hash((self.path, self.row_group))


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------

class DatasetWriter(object):
    """Row-oriented Parquet writer with explicit row-group size control.

    Rows are encoded through the schema's codecs, buffered, and flushed as one
    Parquet row group when the estimated encoded size reaches
    ``row_group_size_mb`` (or ``rows_per_row_group`` rows, if given). A new file
    starts every ``rows_per_file`` rows, enabling multi-file datasets whose files
    can later be read/sharded independently.

    Hive-style partitioning: pass ``partition_by=['field', ...]`` and rows are
    routed to ``field=value/`` subdirectories, one open writer per partition.

    ``compression`` selects the parquet codec: a string applies dataset-wide
    (``'snappy'`` default; ``'zstd'``, ``'lz4'`` and ``'none'`` all decode
    through the same fused native kernel via its first-party decompressors —
    docs/native.md qualification matrix), a dict maps column name -> codec for
    per-column control, and ``None`` means uncompressed. With the string form,
    columns whose codec already compresses its payloads (png/jpeg/zlib cells)
    are written uncompressed automatically (``preferred_column_compression``)
    — re-compressing them costs read-side decompression for zero size win.

    ``append=True`` opens an EXISTING dataset for growth (the tail-following
    ingest contract, docs/sequence.md): part-file names continue past the
    files already recorded in ``_common_metadata``, and the row-group
    inventory written on close MERGES with the existing one instead of
    replacing it. Single-writer only — two concurrent appenders would race
    the metadata rewrite.
    """

    def __init__(self, dataset_url, schema, row_group_size_mb=None, rows_per_row_group=None,
                 rows_per_file=None, partition_by=None, compression='snappy', append=False):
        self._dataset_url = dataset_url
        self._resolver = FilesystemResolver(dataset_url)
        self._fs = self._resolver.filesystem()
        self._root = self._resolver.get_dataset_path()
        self._schema = schema
        self._row_group_bytes = int((row_group_size_mb or DEFAULT_ROW_GROUP_SIZE_MB) * (1 << 20))
        self._rows_per_row_group = rows_per_row_group
        self._rows_per_file = rows_per_file
        self._partition_by = list(partition_by or [])
        for p in self._partition_by:
            if p not in schema.fields:
                raise PetastormTpuError('partition_by field {!r} not in schema'.format(p))
        # per-column compression: codecs whose payloads are already compressed
        # (png/jpeg/zlib cells) opt out of the dataset-default codec — snappy on
        # such columns costs read-side decompression for zero size win
        data_fields_all = [f for f in schema if f.name not in self._partition_by]
        if isinstance(compression, dict):
            self._compression = compression
        else:
            default = compression if compression is not None else 'none'
            overrides = {
                f.name: f.codec.preferred_column_compression for f in data_fields_all
                if getattr(f.codec, 'preferred_column_compression', None) is not None
                and f.codec.preferred_column_compression != default}
            self._compression = ({**{f.name: default for f in data_fields_all},
                                  **overrides} if overrides else compression)
        # physical schema excludes partition columns (they live in the paths)
        data_fields = data_fields_all
        self._arrow_schema = pa.schema(
            [pa.field(f.name, f.codec.arrow_type(f), f.nullable) for f in data_fields])
        self._data_field_names = [f.name for f in data_fields]
        # fixed-size-binary (RawTensorCodec) columns are written
        # dictionary-free — dictionary encoding of unique tensors only
        # bloats — with a data page sized to hold a whole row group: one
        # PLAIN UNCOMPRESSED page per chunk is the layout the zero-copy page
        # scanner (native/pagescan.py) serves as a single mmap view
        fsb = [n for n in self._data_field_names
               if pa.types.is_fixed_size_binary(self._arrow_schema.field(n).type)]
        self._pq_writer_kwargs = {}
        if fsb:
            # in a raw-tensor store, flat REQUIRED numeric siblings (labels,
            # ids) also skip dictionary encoding so the whole read serves
            # zero-copy — otherwise one dict-encoded 8-byte label column
            # forces a full Arrow C++ round trip per row group (~1.1ms
            # measured, dominating the scanned path)
            def _plain(name):
                f = self._arrow_schema.field(name)
                return name in fsb or (not f.nullable and
                                       (pa.types.is_integer(f.type) or
                                        pa.types.is_floating(f.type)))
            self._pq_writer_kwargs['use_dictionary'] = \
                [n for n in self._data_field_names if not _plain(n)]
            per_group = (self._rows_per_row_group *
                         max(self._arrow_schema.field(n).type.byte_width for n in fsb)
                         if self._rows_per_row_group is not None else self._row_group_bytes)
            self._pq_writer_kwargs['data_page_size'] = max(1 << 20, per_group + (64 << 10))
        self._writers = {}  # partition rel-dir -> _PartitionWriter
        self._row_groups_per_file = {}  # relpath -> count
        self._closed = False
        self._fs.create_dir(self._root, recursive=True)
        # append mode: the existing inventory both seeds the merged metadata
        # written on close and tells _open_file which part names are taken
        self._existing_counts = {}
        if append:
            arrow_meta = _read_common_metadata(self._fs, self._root)
            meta = (arrow_meta.metadata or {}) if arrow_meta is not None else {}
            if ROW_GROUPS_PER_FILE_KEY in meta:
                self._existing_counts = json.loads(
                    meta[ROW_GROUPS_PER_FILE_KEY].decode('utf-8'))

    @property
    def row_groups_per_file(self):
        """Full inventory this writer's metadata describes: the pre-existing
        files (append mode) merged with everything written here."""
        return {**self._existing_counts, **self._row_groups_per_file}

    def write(self, row_dict):
        """Encode and buffer one row (a dict of in-memory field values)."""
        if self._closed:
            raise PetastormTpuError('Writer is closed')
        encoded = encode_row(self._schema, row_dict)
        rel_dir = self._partition_dir(encoded)
        writer = self._writers.get(rel_dir)
        if writer is None:
            writer = _PartitionWriter(self, rel_dir)
            self._writers[rel_dir] = writer
        writer.append({k: encoded[k] for k in self._data_field_names})

    def write_batch(self, rows):
        for row in rows:
            self.write(row)

    def _partition_dir(self, encoded_row):
        from urllib.parse import quote
        parts = []
        for key in self._partition_by:
            value = encoded_row[key]
            # percent-escape like hive so '/' etc. cannot corrupt the path
            parts.append('{}={}'.format(key, quote(str(value), safe='')))
        return '/'.join(parts)

    def publish(self, final=False):
        """Make everything written SO FAR visible to readers and stamp an
        atomic snapshot marker (the tail-following contract, docs/sequence.md).

        Flushes and closes every open part file (Parquet footers only exist on
        closed files), rewrites ``_common_metadata`` with the merged row-group
        inventory, then publishes a ``_snapshots/snap-NNNNNN.json`` marker via
        :func:`petastorm_tpu.sequence.tail.publish_snapshot`. The writer stays
        usable — the next :meth:`write` opens a fresh part file, so published
        files are immutable from the moment a snapshot names them.

        :param final: marks the snapshot terminal so tail followers stop
            polling instead of waiting for more data
        :returns: the published snapshot id (int)
        """
        if self._closed:
            raise PetastormTpuError('Writer is closed')
        for writer in self._writers.values():
            writer.close()
        _write_dataset_metadata(self._dataset_url, self._schema, self.row_groups_per_file)
        from petastorm_tpu.sequence.tail import publish_snapshot
        return publish_snapshot(self._dataset_url, final=final)

    def close(self):
        if self._closed:
            return
        for writer in self._writers.values():
            writer.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.close()


class _PartitionWriter(object):
    """Buffers encoded rows for one output directory and emits files/row groups."""

    def __init__(self, parent, rel_dir):
        self._parent = parent
        self._rel_dir = rel_dir
        self._buffer = {name: [] for name in parent._data_field_names}
        self._buffered_bytes = 0
        self._buffered_rows = 0
        self._rows_in_file = 0
        self._file_seq = 0
        self._pq_writer = None
        self._cur_relpath = None

    def append(self, encoded_row):
        for name, value in encoded_row.items():
            self._buffer[name].append(value)
            if isinstance(value, (bytes, str)):
                self._buffered_bytes += len(value)
            else:
                self._buffered_bytes += 8
        self._buffered_rows += 1
        p = self._parent
        if p._rows_per_row_group is not None:
            if self._buffered_rows >= p._rows_per_row_group:
                self._flush_row_group()
        elif self._buffered_bytes >= p._row_group_bytes:
            self._flush_row_group()

    def _open_file(self):
        p = self._parent
        while True:
            basename = 'part-{:05d}.parquet'.format(self._file_seq)
            self._file_seq += 1
            relpath = posixpath.join(self._rel_dir, basename) if self._rel_dir else basename
            # append mode: skip names the existing inventory already owns —
            # a fresh writer restarts its sequence at 0 and would otherwise
            # overwrite the dataset it is meant to grow
            if relpath not in p._existing_counts and relpath not in p._row_groups_per_file:
                break
        full = posixpath.join(p._root, relpath)
        if self._rel_dir:
            p._fs.create_dir(posixpath.join(p._root, self._rel_dir), recursive=True)
        sink = p._fs.open_output_stream(full)
        self._pq_writer = pq.ParquetWriter(sink, p._arrow_schema, compression=p._compression,
                                           **p._pq_writer_kwargs)
        self._cur_relpath = relpath
        self._rows_in_file = 0
        p._row_groups_per_file[relpath] = []

    def _flush_row_group(self):
        if self._buffered_rows == 0:
            return
        p = self._parent
        if self._pq_writer is None:
            self._open_file()
        arrays = [pa.array(self._buffer[name], type=p._arrow_schema.field(name).type)
                  for name in p._data_field_names]
        table = pa.Table.from_arrays(arrays, schema=p._arrow_schema)
        self._pq_writer.write_table(table)  # one call == one row group
        p._row_groups_per_file[self._cur_relpath].append(self._buffered_rows)
        self._rows_in_file += self._buffered_rows
        self._buffer = {name: [] for name in p._data_field_names}
        self._buffered_bytes = 0
        self._buffered_rows = 0
        if p._rows_per_file is not None and self._rows_in_file >= p._rows_per_file:
            self._close_file()

    def _close_file(self):
        if self._pq_writer is not None:
            self._pq_writer.close()
            self._pq_writer = None
            self._cur_relpath = None

    def close(self):
        self._flush_row_group()
        self._close_file()


@contextmanager
def materialize_dataset(dataset_url, schema, row_group_size_mb=None, rows_per_row_group=None,
                        rows_per_file=None, partition_by=None, compression='snappy',
                        append=False):
    """Context manager bracketing a dataset write (reference
    etl/dataset_metadata.py:52-114). Yields a :class:`DatasetWriter`; on exit,
    closes it, writes ``_common_metadata`` with the JSON unischema and per-file
    row-group counts, and validates the dataset is readable.

    :param compression: parquet codec — dataset-wide string (``'snappy'``
        default, ``'zstd'``/``'lz4'``/``'none'`` equally fused-readable), a
        per-column ``{name: codec}`` dict, or ``None`` for uncompressed; see
        :class:`DatasetWriter` for the already-compressed-payload override.
    :param append: grow an existing dataset instead of starting one — part
        names continue past the recorded inventory and the final metadata
        merges with it (see :class:`DatasetWriter`); combine with
        :meth:`DatasetWriter.publish` for tail-following readers."""
    writer = DatasetWriter(dataset_url, schema, row_group_size_mb=row_group_size_mb,
                           rows_per_row_group=rows_per_row_group, rows_per_file=rows_per_file,
                           partition_by=partition_by, compression=compression, append=append)
    try:
        yield writer
    finally:
        # always release ParquetWriters/output streams, even when the caller's
        # with-body raises mid-write
        writer.close()
    _write_dataset_metadata(dataset_url, schema, writer.row_groups_per_file)
    # validation read (reference :117-130)
    pieces = load_row_groups(dataset_url)
    if not pieces:
        raise PetastormMetadataError('Dataset at {} has no row groups after write'.format(dataset_url))


def write_petastorm_dataset(dataset_url, schema, rows, **writer_kwargs):
    """One-shot convenience: write an iterable of row dicts as a dataset."""
    with materialize_dataset(dataset_url, schema, **writer_kwargs) as writer:
        for row in rows:
            writer.write(row)


def _write_dataset_metadata(dataset_url, schema, row_groups_per_file, extra_metadata=None):
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    # physical arrow schema for _common_metadata (partition columns excluded from files,
    # but the unischema JSON captures the full logical schema)
    metadata = {
        UNISCHEMA_KEY: json.dumps(schema.to_json()).encode('utf-8'),
        ROW_GROUPS_PER_FILE_KEY: json.dumps(row_groups_per_file).encode('utf-8'),
    }
    if extra_metadata:
        metadata.update(extra_metadata)
    arrow_schema = schema.as_arrow_schema().with_metadata(metadata)
    with fs.open_output_stream(posixpath.join(root, _COMMON_METADATA)) as sink:
        pq.write_metadata(arrow_schema, sink)


def add_dataset_metadata(dataset_url, key, value_bytes):
    """Rewrite ``_common_metadata`` with an extra key (reference utils.py:90-134)."""
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    meta_path = posixpath.join(root, _COMMON_METADATA)
    existing = _read_common_metadata(fs, root)
    if existing is not None:
        arrow_schema = existing
        md = dict(existing.metadata or {})
    else:
        arrow_schema = pa.schema([])
        md = {}
    md[key] = value_bytes
    with fs.open_output_stream(meta_path) as sink:
        pq.write_metadata(arrow_schema.with_metadata(md), sink)


def _read_common_metadata(fs, root):
    """Return the arrow schema (with KV metadata) stored in _common_metadata, or None."""
    meta_path = posixpath.join(root, _COMMON_METADATA)
    info = fs.get_file_info([meta_path])[0]
    if info.type == pafs.FileType.NotFound:
        return None
    with fs.open_input_file(meta_path) as f:
        return pq.read_schema(f)


def read_metadata_value(dataset_url, key):
    """Read one KV metadata value from _common_metadata (bytes), or None."""
    return read_metadata_dict(dataset_url).get(key)


def read_metadata_dict(dataset_url, retry_policy=None):
    """All KV metadata from _common_metadata as a dict (one footer fetch)."""
    resolver = FilesystemResolver(dataset_url, retry_policy=retry_policy)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    arrow_schema = _read_common_metadata(fs, root)
    if arrow_schema is None or not arrow_schema.metadata:
        return {}
    return dict(arrow_schema.metadata)


# ---------------------------------------------------------------------------
# Reading metadata
# ---------------------------------------------------------------------------

def list_parquet_files(fs, root):
    """Recursively list data files, skipping _/. prefixed entries (metadata,
    Spark markers). Path-sorted for deterministic piece order
    (reference etl/dataset_metadata.py:262-266)."""
    selector = pafs.FileSelector(root, recursive=True)
    infos = fs.get_file_info(selector)
    files = []
    for info in infos:
        if info.type != pafs.FileType.File:
            continue
        base = posixpath.basename(info.path)
        if base.startswith('_') or base.startswith('.') or base.endswith('.crc'):
            continue
        files.append(info.path)
    return sorted(files)


def _parse_partition_value(v, dtype):
    if dtype is np.str_:
        return v
    if dtype is np.bool_:
        # np.bool_('False') is True; parse textually
        return v.strip().lower() in ('true', '1')
    return np.dtype(dtype).type(v).item()


def _partition_keys_from_relpath(relpath, schema=None):
    """Parse hive-style ``key=value`` path components into typed partition keys."""
    from urllib.parse import unquote
    keys = {}
    for component in relpath.split('/')[:-1]:
        if '=' not in component:
            continue
        k, v = component.split('=', 1)
        v = unquote(v)
        if schema is not None and k in schema.fields:
            try:
                keys[k] = _parse_partition_value(v, schema.fields[k].numpy_dtype)
            except (ValueError, TypeError):
                keys[k] = v
        else:
            try:
                keys[k] = int(v)
            except ValueError:
                keys[k] = v
    return keys


def load_row_groups(dataset_url, schema=None, max_footer_read_threads=10,
                    use_cached_metadata=True, retry_policy=None):
    """List all row-group pieces of the dataset with the reference's three-way
    fallback (etl/dataset_metadata.py:231-336):

    1. our ``num_row_groups_per_file`` metadata key (fast path, no footer reads)
    2. a ``_metadata`` summary file
    3. parallel footer reads over all data files

    ``use_cached_metadata=False`` skips paths 1 and 2 and always reads the data
    file footers — the ground truth when stored metadata may be stale (e.g. the
    generate-metadata tool retrofitting a store rewritten by another tool).
    """
    resolver = FilesystemResolver(dataset_url, retry_policy=retry_policy)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    arrow_meta_schema = _read_common_metadata(fs, root)  # single read serves schema + counts
    meta = (arrow_meta_schema.metadata or {}) if arrow_meta_schema is not None else {}
    if schema is None and UNISCHEMA_KEY in meta:
        schema = Unischema.from_json(json.loads(meta[UNISCHEMA_KEY].decode('utf-8')))

    counts = None
    if use_cached_metadata and ROW_GROUPS_PER_FILE_KEY in meta:
        counts = json.loads(meta[ROW_GROUPS_PER_FILE_KEY].decode('utf-8'))
    elif use_cached_metadata:
        from petastorm_tpu.etl import legacy
        if legacy.REF_ROW_GROUPS_PER_FILE_KEY in meta:
            # counts written by the original petastorm library (plain ints)
            counts = legacy.load_legacy_row_group_counts(meta[legacy.REF_ROW_GROUPS_PER_FILE_KEY])
    if counts is not None:
        pieces = []
        for relpath in sorted(counts):
            full = posixpath.join(root, relpath)
            partition_keys = _partition_keys_from_relpath(relpath, schema)
            entry = counts[relpath]
            # value is a list of per-row-group row counts (an int count is
            # accepted for datasets written before row counts were recorded)
            row_counts = entry if isinstance(entry, list) else [None] * entry
            for rg, num_rows in enumerate(row_counts):
                pieces.append(RowGroupPiece(full, rg, num_rows=num_rows,
                                            partition_keys=partition_keys))
        return pieces

    summary_path = posixpath.join(root, _SUMMARY_METADATA)
    if use_cached_metadata and fs.get_file_info([summary_path])[0].type == pafs.FileType.File:
        with fs.open_input_file(summary_path) as f:
            file_meta = pq.read_metadata(f)
        per_file = {}
        for i in range(file_meta.num_row_groups):
            rg = file_meta.row_group(i)
            file_path = rg.column(0).file_path
            if not file_path:
                break  # malformed summary; fall through to footer reads
            per_file.setdefault(file_path, []).append(rg.num_rows)
        else:
            pieces = []
            for relpath in sorted(per_file):
                full = posixpath.join(root, relpath)
                partition_keys = _partition_keys_from_relpath(relpath, schema)
                for rg_idx, num_rows in enumerate(per_file[relpath]):
                    pieces.append(RowGroupPiece(full, rg_idx, num_rows=num_rows,
                                                partition_keys=partition_keys))
            return pieces

    # fallback: read every footer in parallel (reference :323-336)
    files = list_parquet_files(fs, root)

    def footer(path):
        with fs.open_input_file(path) as f:
            md = pq.ParquetFile(f).metadata
            return [(i, md.row_group(i).num_rows) for i in range(md.num_row_groups)]

    with ThreadPoolExecutor(max_workers=max_footer_read_threads) as executor:
        footers = list(executor.map(footer, files))
    pieces = []
    for path, rgs in zip(files, footers):
        relpath = os.path.relpath(path, root).replace(os.sep, '/')
        partition_keys = _partition_keys_from_relpath(relpath, schema)
        for rg_idx, num_rows in rgs:
            pieces.append(RowGroupPiece(path, rg_idx, num_rows=num_rows,
                                        partition_keys=partition_keys))
    return pieces


def _try_get_schema(fs, root):
    arrow_schema = _read_common_metadata(fs, root)
    if arrow_schema is None or not arrow_schema.metadata:
        return None
    if UNISCHEMA_KEY in arrow_schema.metadata:
        return Unischema.from_json(json.loads(arrow_schema.metadata[UNISCHEMA_KEY].decode('utf-8')))
    from petastorm_tpu.etl import legacy
    if legacy.REF_UNISCHEMA_KEY in arrow_schema.metadata:
        # dataset written by the original petastorm library
        return legacy.load_legacy_unischema(arrow_schema.metadata[legacy.REF_UNISCHEMA_KEY])
    return None


def get_schema(dataset_url, retry_policy=None):
    """Load the stored Unischema; raise if the dataset is not a petastorm_tpu
    dataset (reference etl/dataset_metadata.py:339-368)."""
    resolver = FilesystemResolver(dataset_url, retry_policy=retry_policy)
    schema = _try_get_schema(resolver.filesystem(), resolver.get_dataset_path())
    if schema is None:
        raise PetastormMetadataError(
            'Could not find unischema metadata in dataset at {}. Either the dataset was not '
            'written by petastorm_tpu (use make_batch_reader for plain Parquet stores, or run '
            'the generate-metadata tool), or the _common_metadata file was lost.'.format(dataset_url))
    return schema


def get_schema_from_dataset_url(dataset_url, storage_retry_policy=None):
    """Reference-parity alias for :func:`get_schema`; ``storage_retry_policy``
    is threaded through exactly as ``make_reader(storage_retry_policy=)`` does,
    so a user-tuned (or disabled) policy is honored on this path too."""
    return get_schema(dataset_url, retry_policy=storage_retry_policy)


def infer_or_load_unischema(dataset_url, retry_policy=None):
    """Load the stored schema, else infer one from the Parquet/Arrow schema
    (reference etl/dataset_metadata.py:389-397). Hive partition columns are
    included in the inferred schema."""
    resolver = FilesystemResolver(dataset_url, retry_policy=retry_policy)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    schema = _try_get_schema(fs, root)
    if schema is not None:
        return schema
    files = list_parquet_files(fs, root)
    if not files:
        raise PetastormMetadataError('No parquet files found at {}'.format(dataset_url))
    with fs.open_input_file(files[0]) as f:
        arrow_schema = pq.ParquetFile(f).schema_arrow
    unischema = Unischema.from_arrow_schema(arrow_schema)
    # add hive partition columns (reference unischema.py:321-330)
    relpath = os.path.relpath(files[0], root).replace(os.sep, '/')
    partition_keys = _partition_keys_from_relpath(relpath)
    if partition_keys:
        from petastorm_tpu.codecs import ScalarCodec
        from petastorm_tpu.unischema import UnischemaField
        extra = []
        for k, v in partition_keys.items():
            numpy_dtype = np.int64 if isinstance(v, int) else np.str_
            extra.append(UnischemaField(k, numpy_dtype, (), ScalarCodec(), False))
        unischema = Unischema(unischema.name, list(unischema.fields.values()) + extra)
    return unischema
