"""Build and load row-group indexes (reference /root/reference/petastorm/etl/rowgroup_indexing.py).

The reference runs the indexing map over Spark; here it is a local thread-pool
map over row-group pieces (the decode is I/O + C-level work, so threads suffice).
The resulting inverted indexes are stored as JSON in ``_common_metadata``
(reference pickles them, :78-80).
"""

from __future__ import annotations

import json
import logging
from concurrent.futures import ThreadPoolExecutor

import pyarrow.parquet as pq

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.etl.rowgroup_indexers import indexer_from_json
from petastorm_tpu.fs import FilesystemResolver
from petastorm_tpu.unischema import decode_row

logger = logging.getLogger(__name__)


def build_rowgroup_index(dataset_url, indexers, max_workers=10):
    """Map each row-group piece through every indexer, reduce by ``__add__``,
    and store the combined index in dataset metadata
    (reference rowgroup_indexing.py:38-81)."""
    if not indexers:
        raise PetastormTpuError('indexers list must not be empty')
    schema = dataset_metadata.get_schema(dataset_url)
    pieces = dataset_metadata.load_row_groups(dataset_url, schema=schema)
    resolver = FilesystemResolver(dataset_url)
    fs = resolver.filesystem()

    column_names = sorted({c for indexer in indexers for c in indexer.column_names})
    data_columns = [c for c in column_names if c in schema.fields]

    def index_piece(piece_and_index):
        piece, piece_index = piece_and_index
        with fs.open_input_file(piece.path) as f:
            pf = pq.ParquetFile(f)
            cols = [c for c in data_columns if c not in piece.partition_keys]
            table = pf.read_row_group(piece.row_group, columns=cols)
        rows = table.to_pylist()
        for row in rows:
            row.update(piece.partition_keys)
        decoded = [decode_row(row, schema) for row in rows]
        # fresh indexer instances per piece (map step)
        piece_indexers = [indexer_from_json(ix.to_json()) for ix in indexers]
        for ix in piece_indexers:
            ix.build_index(decoded, piece_index)
        return piece_indexers

    with ThreadPoolExecutor(max_workers=max_workers) as executor:
        per_piece = list(executor.map(index_piece, [(p, i) for i, p in enumerate(pieces)]))

    combined = list(per_piece[0])
    for piece_indexers in per_piece[1:]:
        combined = [a + b for a, b in zip(combined, piece_indexers)]

    payload = json.dumps({ix.index_name: ix.to_json() for ix in combined}).encode('utf-8')
    dataset_metadata.add_dataset_metadata(dataset_url, dataset_metadata.ROW_GROUP_INDEX_KEY, payload)
    logger.info('Built %d row-group indexes over %d pieces', len(combined), len(pieces))
    return combined


def get_row_group_indexes(dataset_url, retry_policy=None):
    """Load the stored indexes: dict index_name -> indexer
    (reference rowgroup_indexing.py:138-160)."""
    meta = dataset_metadata.read_metadata_dict(dataset_url, retry_policy=retry_policy)  # one footer fetch serves both keys
    raw = meta.get(dataset_metadata.ROW_GROUP_INDEX_KEY)
    if raw is None:
        from petastorm_tpu.etl import legacy
        legacy_raw = meta.get(legacy.REF_ROW_GROUP_INDEX_KEY)
        if legacy_raw is not None:
            return legacy.load_legacy_rowgroup_indexes(legacy_raw)
        raise PetastormTpuError(
            'Dataset at {} has no row-group index. Run build_rowgroup_index first.'.format(dataset_url))
    spec = json.loads(raw.decode('utf-8'))
    return {name: indexer_from_json(s) for name, s in spec.items()}
