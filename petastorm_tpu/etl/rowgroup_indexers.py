"""Built-in row-group indexers (reference /root/reference/petastorm/etl/rowgroup_indexers.py)."""

from __future__ import annotations

import numpy as np

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.indexer_base import RowGroupIndexerBase

_INDEXER_REGISTRY = {}


def register_indexer(cls):
    _INDEXER_REGISTRY[cls.indexer_type] = cls
    return cls


def indexer_from_json(spec):
    spec = dict(spec)
    indexer_type = spec.pop('indexer_type')
    if indexer_type not in _INDEXER_REGISTRY:
        raise PetastormTpuError('Unknown indexer type {!r}'.format(indexer_type))
    return _INDEXER_REGISTRY[indexer_type].from_json(spec)


def _json_key(value):
    """Normalize an indexed value to a JSON-stable string key."""
    if isinstance(value, bytes):
        value = value.decode('utf-8', errors='replace')
    if isinstance(value, np.generic):
        value = value.item()
    return str(value)


@register_indexer
class SingleFieldIndexer(RowGroupIndexerBase):
    """value-of-field -> set of piece indexes (reference rowgroup_indexers.py:21-75).
    Array fields index every element of the array."""

    indexer_type = 'single_field'

    def __init__(self, index_name, index_field, index_dict=None):
        self._index_name = index_name
        self._column_name = index_field
        self._index_dict = {k: set(v) for k, v in (index_dict or {}).items()}

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._column_name]

    @property
    def indexed_values(self):
        return list(self._index_dict.keys())

    def get_row_group_indexes(self, value_key):
        return self._index_dict.get(_json_key(value_key), set())

    def build_index(self, decoded_rows, piece_index):
        if not decoded_rows:
            raise PetastormTpuError('Cannot build index for empty rows set')
        for row in decoded_rows:
            value = row[self._column_name] if isinstance(row, dict) else getattr(row, self._column_name)
            if value is None:
                continue
            if isinstance(value, np.ndarray):
                for element in value.flat:
                    self._index_dict.setdefault(_json_key(element), set()).add(piece_index)
            else:
                self._index_dict.setdefault(_json_key(value), set()).add(piece_index)
        return self._index_dict

    def __add__(self, other):
        if not isinstance(other, SingleFieldIndexer) or other._column_name != self._column_name:
            raise PetastormTpuError('Cannot merge indexers of different fields')
        merged = SingleFieldIndexer(self._index_name, self._column_name)
        merged._index_dict = {k: set(v) for k, v in self._index_dict.items()}
        for k, v in other._index_dict.items():
            merged._index_dict.setdefault(k, set()).update(v)
        return merged

    def to_json(self):
        return {'indexer_type': self.indexer_type,
                'index_name': self._index_name,
                'index_field': self._column_name,
                'index_dict': {k: sorted(v) for k, v in self._index_dict.items()}}

    @classmethod
    def from_json(cls, spec):
        return cls(spec['index_name'], spec['index_field'], spec['index_dict'])


@register_indexer
class FieldNotNullIndexer(RowGroupIndexerBase):
    """Indexes pieces where the field is not null (reference rowgroup_indexers.py:78-124)."""

    indexer_type = 'field_not_null'
    _KEY = 'not_null'

    def __init__(self, index_name, index_field, piece_indexes=None):
        self._index_name = index_name
        self._column_name = index_field
        self._pieces = set(piece_indexes or ())

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._column_name]

    @property
    def indexed_values(self):
        return [self._KEY]

    def get_row_group_indexes(self, value_key=None):
        return set(self._pieces)

    def build_index(self, decoded_rows, piece_index):
        if not decoded_rows:
            raise PetastormTpuError('Cannot build index for empty rows set')
        for row in decoded_rows:
            value = row[self._column_name] if isinstance(row, dict) else getattr(row, self._column_name)
            if value is not None:
                self._pieces.add(piece_index)
                break
        return self._pieces

    def __add__(self, other):
        if not isinstance(other, FieldNotNullIndexer) or other._column_name != self._column_name:
            raise PetastormTpuError('Cannot merge indexers of different fields')
        return FieldNotNullIndexer(self._index_name, self._column_name, self._pieces | other._pieces)

    def to_json(self):
        return {'indexer_type': self.indexer_type,
                'index_name': self._index_name,
                'index_field': self._column_name,
                'piece_indexes': sorted(self._pieces)}

    @classmethod
    def from_json(cls, spec):
        return cls(spec['index_name'], spec['index_field'], spec['piece_indexes'])
