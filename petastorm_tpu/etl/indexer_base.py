"""Row-group indexer protocol (reference /root/reference/petastorm/etl/__init__.py:21-50)."""

from __future__ import annotations


class RowGroupIndexerBase(object):
    """Base class for row-group indexers: map decoded rows of each row group to
    a value -> {piece indexes} inverted index used by row-group selectors."""

    @property
    def index_name(self):
        """Unique name of this index."""
        raise NotImplementedError

    @property
    def column_names(self):
        """Columns the indexer needs read+decoded to build the index."""
        raise NotImplementedError

    @property
    def indexed_values(self):
        """All values present in the index."""
        raise NotImplementedError

    def get_row_group_indexes(self, value_key):
        """Set of row-group (piece) indexes containing ``value_key``."""
        raise NotImplementedError

    def build_index(self, decoded_rows, piece_index):
        """Consume decoded rows of one row group, record them under ``piece_index``."""
        raise NotImplementedError

    def __add__(self, other):
        """Merge two indexers of the same type/name (reduce step)."""
        raise NotImplementedError

    def to_json(self):
        raise NotImplementedError
