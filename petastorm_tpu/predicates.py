"""Row predicates with a column-pruning contract.

Parity: /root/reference/petastorm/predicates.py:26-183. A predicate declares the
fields it needs (``get_fields``) so workers read/decode only those columns first,
evaluate the mask, and early-exit empty row groups before touching heavy columns
(the reference's in-worker pushdown, py_dict_reader_worker.py:188-252). When all
predicate fields are partition keys, the reader evaluates the predicate at the
piece level and drops whole row groups without any I/O.
"""

from __future__ import annotations

import hashlib

import numpy as np


class PredicateBase(object):
    def get_fields(self):
        """Names of fields ``do_include`` needs."""
        raise NotImplementedError

    def do_include(self, values):
        """values: dict field_name -> decoded value for one row. Return True to
        keep the row."""
        raise NotImplementedError

    def do_include_batch(self, block):
        """Optional vectorized evaluation: ``block`` is a dict of whole decoded
        columns (``[N]``/``[N, ...]`` arrays); return a boolean ``[N]`` mask, or
        ``None`` to make the worker fall back to per-row :meth:`do_include`.
        Predicates that can answer column-at-a-time (``in_set``, compositions
        thereof) keep the pushdown path free of per-row Python — the row-worker
        analog of the reference's vectorized pandas predicate, which it only
        gave the batch worker (arrow_reader_worker.py:181-240)."""
        return None

    def native_clauses(self):
        """AND-of-clauses description for the fused native predicate stage, or
        ``None`` when this predicate cannot be pushed below the GIL (the
        worker then evaluates it in Python as before). Each clause is a dict
        ``{'field', 'op': 'in'|'range', 'negate'}`` plus ``'values'`` (in) or
        ``'lo'/'hi'/'lo_incl'/'hi_incl'`` (range); clauses are ANDed row-wise.
        Semantics MUST match :meth:`do_include` exactly — the worker trusts
        the native verdict without re-checking (see docs/native.md for the
        qualification matrix)."""
        return None


def evaluate_predicate_mask(predicate, block, num_rows):
    """THE contract enforcement for :meth:`PredicateBase.do_include_batch`,
    shared by both workers' pushdown paths: returns a validated boolean mask,
    or ``None`` when the predicate has no batch path / declined (callers fall
    back to per-row ``do_include``)."""
    mask = _batch_mask(predicate, block)
    if mask is None:
        return None
    mask = np.asarray(mask)
    if mask.ndim != 1 or len(mask) != num_rows:
        raise ValueError(
            'do_include_batch must return a 1-D mask with one entry per row; '
            'got shape {} for {} rows'.format(mask.shape, num_rows))
    return mask.astype(bool, copy=False)


def _batch_mask(predicate, block):
    """The optional-batch contract in one place: a predicate without
    ``do_include_batch`` (duck-typed, row-only) declines with ``None``, same
    as one whose batch path returns ``None``."""
    batch_fn = getattr(predicate, 'do_include_batch', None)
    if batch_fn is None:
        return None
    return batch_fn(block)


def _native_semantics_intact(predicate, base):
    """A subclass that overrides ``do_include``/``do_include_batch`` changed
    the predicate's semantics: the base class's clause description no longer
    speaks for it, and the native pushdown — which trusts the clauses without
    re-checking — must decline rather than silently evaluate the BASE
    semantics below the GIL."""
    cls = type(predicate)
    return (cls.do_include is base.do_include and
            cls.do_include_batch is base.do_include_batch)


class in_set(PredicateBase):
    """Keep rows whose scalar field value is in ``inclusion_values``."""

    def __init__(self, inclusion_values, field_name):
        self._inclusion_values = set(inclusion_values)
        self._field_name = field_name

    def get_fields(self):
        return {self._field_name}

    def do_include(self, values):
        return values[self._field_name] in self._inclusion_values

    def do_include_batch(self, block):
        col = block[self._field_name]
        if not isinstance(col, np.ndarray) or col.ndim != 1:
            return None
        # np.isin silently COERCES mixed-type inclusion lists (e.g. ['a', 1]
        # becomes a unicode array and 1 stops matching int columns) instead of
        # raising — so only vectorize when the values demonstrably share the
        # column's comparison domain; anything else keeps per-row semantics
        vals = list(self._inclusion_values)
        if col.dtype.kind in 'biuf':
            ok = all(isinstance(v, (int, float, np.number)) and not isinstance(v, (str, bytes))
                     for v in vals)
        elif col.dtype.kind == 'U':
            ok = all(isinstance(v, str) for v in vals)
        elif col.dtype.kind == 'S':
            ok = all(isinstance(v, bytes) for v in vals)
        elif col.dtype == object:
            ok = (all(isinstance(v, str) for v in vals) and
                  all(isinstance(v, str) for v in col))
        else:
            ok = False
        if not ok:
            return None
        return np.isin(col, vals)

    def native_clauses(self):
        if not _native_semantics_intact(self, in_set):
            return None
        vals = list(self._inclusion_values)
        # numeric/bool membership is the natively-evaluable shape; string and
        # mixed-type sets keep the Python path (same domain caution as the
        # vectorized branch above)
        if not all(isinstance(v, (bool, int, float, np.bool_, np.integer,
                                  np.floating))
                   and not isinstance(v, (str, bytes)) for v in vals):
            return None
        return [{'field': self._field_name, 'op': 'in', 'values': vals,
                 'negate': False}]


class in_range(PredicateBase):
    """Keep rows whose scalar field value lies between ``lo`` and ``hi``
    (either bound optional, inclusivity configurable). This is the canonical
    natively-pushable range predicate: on qualifying stores the fused kernel
    evaluates it below the GIL and skips whole pages via min/max page
    statistics before decoding anything (docs/native.md)."""

    def __init__(self, field_name, lo=None, hi=None, lo_inclusive=True,
                 hi_inclusive=True):
        if lo is None and hi is None:
            raise ValueError('in_range needs at least one bound')
        self._field_name = field_name
        self._lo = lo
        self._hi = hi
        self._lo_inclusive = bool(lo_inclusive)
        self._hi_inclusive = bool(hi_inclusive)

    def get_fields(self):
        return {self._field_name}

    def _in_range(self, v):
        if self._lo is not None:
            ok = v >= self._lo if self._lo_inclusive else v > self._lo
            if not ok:
                return False
        if self._hi is not None:
            ok = v <= self._hi if self._hi_inclusive else v < self._hi
            if not ok:
                return False
        return True

    def do_include(self, values):
        return bool(self._in_range(values[self._field_name]))

    def do_include_batch(self, block):
        col = block[self._field_name]
        if not isinstance(col, np.ndarray) or col.ndim != 1 \
                or col.dtype.kind not in 'biuf':
            return None
        mask = np.ones(len(col), dtype=bool)
        with np.errstate(invalid='ignore'):
            if self._lo is not None:
                mask &= (col >= self._lo) if self._lo_inclusive else (col > self._lo)
            if self._hi is not None:
                mask &= (col <= self._hi) if self._hi_inclusive else (col < self._hi)
        return mask

    def native_clauses(self):
        if not _native_semantics_intact(self, in_range):
            return None
        return [{'field': self._field_name, 'op': 'range', 'lo': self._lo,
                 'hi': self._hi, 'lo_incl': self._lo_inclusive,
                 'hi_incl': self._hi_inclusive, 'negate': False}]


class in_intersection(PredicateBase):
    """Keep rows whose array field intersects ``inclusion_values``."""

    def __init__(self, inclusion_values, field_name):
        self._inclusion_values = set(inclusion_values)
        self._field_name = field_name

    def get_fields(self):
        return {self._field_name}

    def _cell_intersects(self, value):
        """THE intersection semantics (None excluded; arrays compared over
        ``.flat``), shared by the row and batched paths."""
        if value is None:
            return False
        return not self._inclusion_values.isdisjoint(
            v for v in (value.flat if isinstance(value, np.ndarray) else value))

    def do_include(self, values):
        return self._cell_intersects(values[self._field_name])

    def do_include_batch(self, block):
        col = block[self._field_name]
        if not isinstance(col, np.ndarray):
            return None
        if col.ndim >= 2 and col.dtype.kind in 'biuf':
            # uniform stacked cells: one vectorized isin over the flattened
            # tail axes (same mixed-type guard as in_set — np.isin silently
            # coerces e.g. strings against numeric columns)
            vals = list(self._inclusion_values)
            if not all(isinstance(v, (int, float, np.number)) and not isinstance(v, (str, bytes))
                       for v in vals):
                return None
            return np.isin(col.reshape(len(col), -1), vals).any(axis=1)
        if col.ndim == 1 and col.dtype == object:
            # ragged cells: per-cell set probe, but no per-row dict churn
            return np.fromiter((self._cell_intersects(v) for v in col),
                               dtype=bool, count=len(col))
        return None


class in_lambda(PredicateBase):
    """Arbitrary user predicate over the named fields; optional mutable state
    object is passed as a second argument when provided."""

    def __init__(self, predicate_fields, predicate_func, state=None):
        self._predicate_fields = list(predicate_fields)
        self._predicate_func = predicate_func
        self._state = state

    def get_fields(self):
        return set(self._predicate_fields)

    def do_include(self, values):
        if self._state is None:
            return self._predicate_func(values)
        return self._predicate_func(values, self._state)


class in_negate(PredicateBase):
    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)

    def do_include_batch(self, block):
        inner = _batch_mask(self._predicate, block)
        return None if inner is None else ~np.asarray(inner, dtype=bool)

    def native_clauses(self):
        if not _native_semantics_intact(self, in_negate):
            return None
        inner = getattr(self._predicate, 'native_clauses', lambda: None)()
        if inner is None or len(inner) != 1:
            # NOT over an AND of several clauses is not an AND of clauses
            return None
        cl = dict(inner[0])
        cl['negate'] = not cl.get('negate')
        return [cl]


class in_reduce(PredicateBase):
    """Compose predicates with a reduction over their booleans, e.g.
    ``in_reduce([p1, p2], all)`` or ``in_reduce([p1, p2], any)``."""

    def __init__(self, predicate_list, reduce_func):
        self._predicate_list = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        fields = set()
        for p in self._predicate_list:
            fields |= set(p.get_fields())
        return fields

    def do_include(self, values):
        return self._reduce_func([p.do_include(values) for p in self._predicate_list])

    def do_include_batch(self, block):
        if self._reduce_func is all:
            combine = np.logical_and.reduce
        elif self._reduce_func is any:
            combine = np.logical_or.reduce
        else:
            return None  # arbitrary reducers keep row-at-a-time semantics
        masks = []
        for p in self._predicate_list:
            m = _batch_mask(p, block)
            if m is None:
                return None
            masks.append(np.asarray(m, dtype=bool))
        return combine(masks)

    def native_clauses(self):
        if not _native_semantics_intact(self, in_reduce):
            return None
        if self._reduce_func is not all:
            return None  # only conjunctions are an AND of clauses
        out = []
        for p in self._predicate_list:
            cls = getattr(p, 'native_clauses', lambda: None)()
            if cls is None:
                return None
            out.extend(cls)
        return out or None


class in_pseudorandom_split(PredicateBase):
    """Deterministic hash-bucket train/val/test split on a field
    (reference predicates.py:144-183).

    ``fraction_list`` are the subset fractions (must sum to <= 1.0);
    ``subset_index`` selects which subset this predicate keeps. The same field
    value always lands in the same subset, across runs and processes.
    """

    _BUCKETS = 2 ** 32

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError('subset_index {} out of range for {} fractions'.format(
                subset_index, len(fraction_list)))
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError('fractions must sum to <= 1.0, got {}'.format(sum(fraction_list)))
        cumsum = np.cumsum([0.0] + list(fraction_list))
        self._low = cumsum[subset_index]
        self._high = cumsum[subset_index + 1]
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def _in_bucket(self, value):
        raw = value if isinstance(value, bytes) else str(value).encode('utf-8')
        bucket = int.from_bytes(hashlib.md5(raw).digest()[:4], 'big') / self._BUCKETS
        return self._low <= bucket < self._high

    def do_include(self, values):
        return self._in_bucket(values[self._predicate_field])

    def do_include_batch(self, block):
        col = block[self._predicate_field]
        if not isinstance(col, np.ndarray) or col.ndim != 1:
            return None
        # the md5 per value is inherent (split stability contract); batching
        # still skips the per-row dict materialization of the fallback path
        return np.fromiter((self._in_bucket(v) for v in col), dtype=bool, count=len(col))
