"""PT1400 — sequence sampling/packing decisions must be deterministic.

The sequence data plane's acceptance bar (``docs/sequence.md``) is
bit-exact reproducibility under a fixed seed: the same seed must reproduce
the same mixture interleaving, the same bucket release order, and the same
packed batches — that is what makes a training run's data order a
checkpointable fact rather than an accident.  The lexically checkable ways
to lose it:

* **wall-clock reads** (``time.time()``, ``datetime.now()``, …) — a
  clock-derived sampling decision is different on every run;
* **module-global RNG draws** (``random.random()``, ``np.random.shuffle``)
  — the process-global stream is shared with whoever else imports
  ``random``, so a seed set elsewhere (or not at all) silently changes the
  data order;
* **RNG constructors without an explicit seed** (``default_rng()``,
  ``Random()``) — OS entropy gives every run a private stream.  Seeded
  constructors (``default_rng(seed)``) are exactly the intended pattern,
  including ``seed=None`` *variables* threaded from a user knob: the rule
  rejects only the lexically-unseeded forms.

The rule scopes to the modules that make sampling/ordering decisions
(mixture, bucketing, packing, the weighted base reader).  The
tail-following reader is deliberately OUT of scope: its poll cadence
legitimately reads clocks — IO pacing is not a sampling decision.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, add_parents, walk_functions
from petastorm_tpu.analysis.elastic_lints import (_GLOBAL_RNG,
                                                  _NP_RANDOM_PREFIXES,
                                                  _SEEDED_CTORS, _WALL_CLOCK,
                                                  _call_chain, _tail,
                                                  _unseeded_ctor)


class SequenceDeterminismChecker(Checker):
    code = 'PT1400'
    name = 'sequence-sampling-determinism'
    description = ('mixture sampling, bucket release and packing decisions '
                   'must be reproducible under a fixed seed: wall-clock '
                   'reads, global-RNG draws and unseeded RNG constructors '
                   'make the data order an accident')
    scope = ('*sequence/mixture*.py', '*sequence/packing*.py',
             '*sequence/bucket*.py', '*weighted_sampling_reader*.py')

    def check(self, src):
        add_parents(src.tree)
        for func, _cls in walk_functions(src.tree):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                for finding in self._check_call(src, node):
                    yield finding

    def _check_call(self, src, call):
        chain = _call_chain(call)
        if chain is None:
            return
        if chain in _WALL_CLOCK:
            yield self.finding(
                src, call.lineno,
                '{}() reads a wall clock inside sequence sampling/packing '
                'code: the decision differs on every run — derive it from '
                'the seeded stream or the data itself'.format(chain))
            return
        if chain in _GLOBAL_RNG or any(
                chain.startswith(p) and _tail(chain) not in _SEEDED_CTORS
                for p in _NP_RANDOM_PREFIXES):
            yield self.finding(
                src, call.lineno,
                '{}() draws from the process-global RNG stream: any other '
                'import of random/np.random perturbs the data order — use a '
                'generator constructed from the ctor seed'.format(chain))
            return
        if _unseeded_ctor(call, chain):
            yield self.finding(
                src, call.lineno,
                '{}() constructed without an explicit seed: OS entropy gives '
                'every run a different data order — thread the ctor seed '
                'through (seed=None from a user knob is fine; a lexically '
                'missing seed is not)'.format(chain))
