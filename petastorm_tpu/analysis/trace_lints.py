"""PT703 — trace-context propagation discipline.

The causal span tree (``observability/trace.py``, docs/observability.md
"Causal tracing") only reconstructs if every span recorded on the data path
derives its ``trace``/``span``/``parent`` identity from the thread's active
:class:`TraceContext` — the one the pools propagate alongside the work item.
A span that mints its own identity is an **orphan**: it lands in the ring but
hangs off no batch's tree, so the critical-path view silently loses exactly
the stage someone hand-instrumented. Two spellings produce orphans, and both
are mechanical to catch:

* a direct ``record_span(...)`` call (any receiver): the low-level emitter
  stamps nothing — identity must come from a ``span``/``stage`` context
  manager (or ``instant``), which reads the active context;
* a ``span(...)``/``stage(...)``/``instant(...)`` call passing an explicit
  ``trace=``, ``span=``, or ``parent=`` keyword: hand-rolled identity
  diverges from the propagated context the moment a retry, requeue, or serve
  re-dispatch renumbers the item. Adopt a context discovered mid-flight with
  ``sp.link(ctx)``; install one around a block with ``obs.use_trace(ctx)``.

The rule binds the propagation path only — worker pools, the row/batch
workers, and the serve plane — where an orphan breaks the cross-process tree
acceptance (a batch must reconstruct ≥4 causally-linked stages). Framework
code (``observability/``) and tests construct raw events legitimately.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker

#: span-opening callables whose identity must come from the active context
_SPAN_OPENERS = frozenset({'span', 'stage', 'instant', 'decision_span'})

#: kwargs that hand-roll causal identity instead of inheriting it
_IDENTITY_KWARGS = frozenset({'trace', 'span', 'parent'})


def _call_name(call):
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class TraceContextChecker(Checker):
    code = 'PT703'
    name = 'trace-context-propagation'
    description = ('spans on the worker/serve data path must inherit the '
                   'propagated TraceContext: no raw record_span calls, no '
                   'hand-rolled trace=/span=/parent= identity — orphan spans '
                   'drop out of every batch tree')
    scope = ('*workers/*.py', '*serve/*.py', '*row_worker.py',
             '*batch_worker.py')

    def check(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == 'record_span':
                yield self.finding(
                    src, node.lineno,
                    'record_span(...) called directly on the propagation path: '
                    'the raw emitter stamps no TraceContext, so the span is an '
                    'orphan in every batch tree — open it with obs.span()/'
                    'obs.stage() (inside use_trace/link) instead')
            elif name in _SPAN_OPENERS:
                rolled = sorted(kw.arg for kw in node.keywords
                                if kw.arg in _IDENTITY_KWARGS)
                if rolled:
                    yield self.finding(
                        src, node.lineno,
                        '{}(...) passes hand-rolled causal identity ({}): '
                        'identity must come from the active TraceContext — '
                        'wrap the block in obs.use_trace(ctx) or adopt a '
                        'late-discovered parent with sp.link(ctx)'.format(
                            name, ', '.join('{}='.format(k) for k in rolled)))
