"""PT400 — purity of functions that JAX traces.

``jax.jit``/``pjit``/``shard_map`` trace a function ONCE and replay the
recorded computation: host-side effects inside it run at trace time only
(``np.random``/``random``/``time.*`` values freeze into constants baked into
the compiled executable), ``.item()``/``.tolist()`` force a blocking
device->host sync (or a ConcretizationTypeError on abstract tracers), and
in-place mutation of an argument or closed-over ndarray writes to a tracer or
leaks a stale host buffer. Generic linters cannot know which functions JAX
traces; this rule resolves the repo's jit idioms:

* ``@jax.jit`` / ``@jit`` / ``@pjit`` decorators
* ``@functools.partial(jax.jit, ...)`` / ``@partial(jit, ...)`` (also for
  ``shard_map``)
* ``jax.jit(fn)`` / ``jax.shard_map(fn, ...)`` calls whose argument names a
  function defined in the same module

and checks those functions plus their nested ``def``s (inner closures trace
with the outer function).
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, attr_chain

_TRACERS = {'jit', 'pjit', 'shard_map'}

#: dotted-call prefixes that are host-side effects under trace
_IMPURE_PREFIXES = ('np.random.', 'numpy.random.', 'random.', 'time.',
                    'datetime.datetime.now', 'datetime.datetime.utcnow',
                    'os.urandom', 'uuid.')

#: method calls forcing device->host sync / concretization
_SYNC_METHODS = {'item', 'tolist'}


def _tracer_name(node):
    """'jit'/'pjit'/'shard_map' when ``node`` references one, else None."""
    chain = attr_chain(node)
    if chain is None:
        return None
    last = chain.rsplit('.', 1)[-1]
    return last if last in _TRACERS else None


def _decorator_traces(dec):
    """Does this decorator make the function traced?"""
    if _tracer_name(dec):
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnames=...) or @functools.partial(jax.jit, ...)
        if _tracer_name(dec.func):
            return True
        chain = attr_chain(dec.func) or ''
        if chain.rsplit('.', 1)[-1] == 'partial' and dec.args \
                and _tracer_name(dec.args[0]):
            return True
    return False


def _collect_traced_functions(tree):
    """FunctionDef nodes that JAX traces, via decorators or jit(fn) calls."""
    by_name = {}
    traced = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            if any(_decorator_traces(d) for d in node.decorator_list):
                traced.append(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _tracer_name(node.func) and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in by_name:
                fn = by_name[arg.id]
                if fn not in traced:
                    traced.append(fn)
    return traced


class JaxPurityChecker(Checker):
    code = 'PT400'
    name = 'jax-purity'
    description = ('host-side effects (np.random/time/.item()/argument mutation) '
                   'inside functions traced by jit/pjit/shard_map')
    scope = ('*jax/*.py', '*ops/*.py', '*parallel/*.py')

    def check(self, src):
        for fn in _collect_traced_functions(src.tree):
            yield from self._check_traced(src, fn)

    def _check_traced(self, src, fn):
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        # two passes: first collect every plain-Name binding in the function
        # (any walk order), then judge subscript writes against that set — a
        # name never bound locally is an argument or a closed-over array
        local_names = set(params)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]):
                        if isinstance(el, ast.Name):
                            local_names.add(el.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for el in (node.target.elts if isinstance(node.target, (ast.Tuple, ast.List))
                           else [node.target]):
                    if isinstance(el, ast.Name):
                        local_names.add(el.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is not None and chain.startswith(_IMPURE_PREFIXES):
                    yield self.finding(
                        src, node.lineno,
                        "'{}()' inside traced function {}() runs at trace time "
                        'only — its value freezes into the compiled executable; '
                        'use jax.random / pass values as arguments'.format(
                            chain, fn.name))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_METHODS and not node.args:
                    yield self.finding(
                        src, node.lineno,
                        ".{}() inside traced function {}() forces a device sync "
                        'and fails on abstract tracers — keep values as jax '
                        'arrays'.format(node.func.attr, fn.name))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                        name = t.value.id
                        if name in params or name not in local_names:
                            yield self.finding(
                                src, t.lineno,
                                "in-place subscript write to '{}' inside traced "
                                'function {}() mutates an argument or closure — '
                                'use .at[...].set(...)'.format(name, fn.name))
