"""SARIF 2.1.0 output for the linter (``petastorm-tpu-lint --format sarif``).

SARIF (Static Analysis Results Interchange Format, OASIS standard) is the
log format CI forges ingest to annotate pull requests. One run per
invocation: the ``tool.driver`` block lists every registered rule id (so a
viewer can show the rule catalog), each finding becomes a ``result`` with a
``physicalLocation``, and suppressed findings (``# noqa`` / baseline) carry
a ``suppressions`` entry — SARIF's native way to say "present but not
actionable" (``kind: inSource`` for noqa, ``kind: external`` for the
baseline ledger). Only unsuppressed results should gate a build, matching
the CLI's exit-code contract.

The emitted document is deliberately minimal-but-valid: every property used
here is required or recommended by the 2.1.0 schema, and
``tests/test_static_analysis.py`` structurally validates the output against
the subset of the schema the linter relies on.
"""

from __future__ import annotations

SARIF_VERSION = '2.1.0'
SARIF_SCHEMA = 'https://json.schemastore.org/sarif-2.1.0.json'

#: Finding.status -> SARIF suppression kind (open findings get none)
_SUPPRESSION_KINDS = {'noqa': 'inSource', 'baselined': 'external'}


def sarif_rules(checkers):
    """The ``tool.driver.rules`` array: one reportingDescriptor per rule id,
    in registration order, plus the framework's PT000 parse-error rule."""
    rules = []
    for cls in checkers:
        for code in cls.rule_codes():
            rules.append({
                'id': code,
                'name': cls.name,
                'shortDescription': {'text': cls.description or cls.name},
            })
    rules.append({
        'id': 'PT000',
        'name': 'parse-error',
        'shortDescription': {'text': 'source file failed to parse'},
    })
    return rules


def to_sarif(findings, checkers):
    """Serialize ``findings`` (any status) into one SARIF 2.1.0 log dict."""
    rules = sarif_rules(checkers)
    rule_index = {r['id']: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        region = {'startLine': f.line}
        if f.snippet:
            region['snippet'] = {'text': f.snippet}
        result = {
            'ruleId': f.code,
            'level': 'error',
            'message': {'text': f.message},
            'locations': [{
                'physicalLocation': {
                    'artifactLocation': {'uri': f.path},
                    'region': region,
                },
            }],
        }
        if f.code in rule_index:
            result['ruleIndex'] = rule_index[f.code]
        kind = _SUPPRESSION_KINDS.get(f.status)
        if kind is not None:
            result['suppressions'] = [{'kind': kind}]
        results.append(result)
    return {
        '$schema': SARIF_SCHEMA,
        'version': SARIF_VERSION,
        'runs': [{
            'tool': {
                'driver': {
                    'name': 'petastorm-tpu-lint',
                    'informationUri':
                        'https://github.com/petastorm-tpu/petastorm-tpu'
                        '/blob/main/docs/analysis.md',
                    'rules': rules,
                },
            },
            'results': results,
        }],
    }


__all__ = ['SARIF_SCHEMA', 'SARIF_VERSION', 'sarif_rules', 'to_sarif']
