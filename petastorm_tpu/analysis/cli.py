"""CLI for the first-party linter.

::

    python -m petastorm_tpu.analysis [paths ...] [options]
    petastorm-tpu-lint [paths ...] [options]

Default path is the installed ``petastorm_tpu`` package.

Exit-code contract (stable; scripts and CI may rely on it):

* ``0`` — clean: no findings remain after noqa suppression, baseline
  absorption and ``--select``/``--ignore`` filtering (also: ``--rules`` and
  ``--write-baseline`` succeeded).
* ``1`` — findings remain (each printed to stdout).
* ``2`` — usage error: unknown option, missing path, or a ``--select``/
  ``--ignore`` token that matches no known rule family.

``--select``/``--ignore`` take comma-separated rule-id prefixes and make
staged rollouts possible: ship new rule families dark with ``--ignore PT8``,
or gate a single family with ``--select PT8``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: the documented exit-code contract
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2


def _default_target():
    import petastorm_tpu
    return os.path.dirname(os.path.abspath(petastorm_tpu.__file__))


def build_parser():
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-lint',
        description='Repo-specific invariant linter: lock discipline (PT100), '
                    'resource lifecycle (PT200), exception hygiene (PT300), JAX '
                    'purity (PT400), native-buffer safety (PT500), hashability '
                    '(PT600). See docs/analysis.md.')
    parser.add_argument('paths', nargs='*',
                        help='files/directories to scan (default: the installed '
                             'petastorm_tpu package)')
    parser.add_argument('--format', choices=('text', 'json'), default='text')
    parser.add_argument('--baseline', metavar='FILE',
                        help='analysis_baseline.json absorbing known findings '
                             '(missing file = empty baseline)')
    parser.add_argument('--write-baseline', metavar='FILE',
                        help='write the current findings as a baseline and exit 0')
    parser.add_argument('--select', metavar='CODES',
                        help='comma-separated rule-id prefixes to report '
                             '(e.g. PT1,PT500); everything else is dropped')
    parser.add_argument('--ignore', metavar='CODES',
                        help='comma-separated rule-id prefixes to suppress '
                             '(applied after --select) — stage a new family '
                             'dark with e.g. --ignore PT8')
    parser.add_argument('--rules', action='store_true',
                        help='list the rule families and exit')
    return parser


def main(argv=None):
    from petastorm_tpu.analysis import ALL_CHECKERS, run_analysis
    from petastorm_tpu.analysis.core import load_baseline, write_baseline

    args = build_parser().parse_args(argv)

    if args.rules:
        for cls in ALL_CHECKERS:
            print('{:<7} {:<22} {}'.format(cls.code, cls.name, cls.description))
        return EXIT_CLEAN

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print('error: no such path: {}'.format(p), file=sys.stderr)
            return EXIT_USAGE

    def parse_prefixes(raw, flag):
        if not raw:
            return None
        prefixes = [c.strip().upper() for c in raw.split(',') if c.strip()]
        known = [cls.code for cls in ALL_CHECKERS] + ['PT000']
        for prefix in prefixes:
            if not any(code.startswith(prefix) for code in known):
                print('error: {} prefix {!r} matches no known rule family '
                      '(see --rules)'.format(flag, prefix), file=sys.stderr)
                return EXIT_USAGE
        return prefixes

    select = parse_prefixes(args.select, '--select')
    if select == EXIT_USAGE:
        return EXIT_USAGE
    ignore = parse_prefixes(args.ignore, '--ignore')
    if ignore == EXIT_USAGE:
        return EXIT_USAGE
    baseline = load_baseline(args.baseline) if args.baseline else None
    findings = run_analysis(paths, baseline=baseline, select=select, ignore=ignore)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print('baseline with {} entr{} written to {}'.format(
            len(findings), 'y' if len(findings) == 1 else 'ies', args.write_baseline))
        return EXIT_CLEAN

    if args.format == 'json':
        print(json.dumps({'findings': [f.to_dict() for f in findings],
                          'count': len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.format())
            if f.snippet:
                print('    {}'.format(f.snippet))
        print('{} finding{}'.format(len(findings), '' if len(findings) == 1 else 's'))
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
