"""CLI for the first-party linter.

::

    python -m petastorm_tpu.analysis [paths ...] [options]
    petastorm-tpu-lint [paths ...] [options]

Default path is the installed ``petastorm_tpu`` package.

Exit-code contract (stable; scripts and CI may rely on it):

* ``0`` — clean: no OPEN findings remain after noqa suppression, baseline
  absorption and ``--select``/``--ignore`` filtering (also: ``--rules`` and
  ``--write-baseline`` succeeded).
* ``1`` — open findings remain (each printed to stdout).
* ``2`` — usage error: unknown option, missing path, or a ``--select``/
  ``--ignore`` token that matches no known rule family.

``--format json`` emits ONE machine-readable finding object per line
(JSONL), so CI and the Admin tooling can annotate diffs line by line:

    {"rule": "PT900", "path": "native/fused.py", "line": 84,
     "message": "...", "snippet": "...", "status": "open"}

``status`` is ``open`` (actionable; these drive the exit code),
``noqa`` (suppressed on its line) or ``baselined`` (absorbed by
``--baseline``) — the JSON stream carries all three so a diff annotator can
show suppressed findings too; text output prints only open ones.

``--select``/``--ignore`` take comma-separated rule-id prefixes and make
staged rollouts possible: ship new rule families dark with ``--ignore PT9``,
or gate a single family with ``--select PT9``.

``--changed`` scans only files git considers modified (tracked files
differing from HEAD, staged or not, plus untracked non-ignored files) —
the edit-loop mode. ``--cache DIR`` keeps a content-addressed per-file
result store so untouched files cost one ``stat`` on re-runs; the
invalidation contract (file bytes + sibling native sources + the analysis
package itself) lives in :mod:`petastorm_tpu.analysis.cache` and
docs/analysis.md. Both compose with every other flag: select/ignore/
baseline are re-applied per run, never baked into cached entries.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: the documented exit-code contract
EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2


def _default_target():
    import petastorm_tpu
    return os.path.dirname(os.path.abspath(petastorm_tpu.__file__))


def build_parser():
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-lint',
        description='Repo-specific invariant linter: lock discipline (PT100), '
                    'resource lifecycle (PT200), exception hygiene (PT300), JAX '
                    'purity (PT400), native-buffer safety (PT500), hashability '
                    '(PT600), telemetry/worker/autotune hygiene (PT7xx), '
                    'protocol discipline (PT8xx), cross-language ABI '
                    'conformance + C++ overflow/bounds (PT9xx). '
                    'See docs/analysis.md.')
    parser.add_argument('paths', nargs='*',
                        help='files/directories to scan (default: the installed '
                             'petastorm_tpu package)')
    parser.add_argument('--format', choices=('text', 'json', 'sarif'),
                        default='text',
                        help='json = one finding object per line (JSONL; '
                             'includes noqa/baselined findings with their '
                             'status — only "open" ones affect the exit code); '
                             'sarif = one SARIF 2.1.0 document (suppressed '
                             'findings carry a "suppressions" entry) for CI '
                             'PR annotation')
    parser.add_argument('--baseline', metavar='FILE',
                        help='analysis_baseline.json absorbing known findings '
                             '(missing file = empty baseline)')
    parser.add_argument('--write-baseline', metavar='FILE',
                        help='write the current findings as a baseline and exit 0')
    parser.add_argument('--select', metavar='CODES',
                        help='comma-separated rule-id prefixes to report '
                             '(e.g. PT1,PT500); everything else is dropped')
    parser.add_argument('--ignore', metavar='CODES',
                        help='comma-separated rule-id prefixes to suppress '
                             '(applied after --select) — stage a new family '
                             'dark with e.g. --ignore PT8')
    parser.add_argument('--changed', action='store_true',
                        help='scan only files git reports as changed vs HEAD '
                             '(plus untracked) under the given paths — the '
                             'edit-loop mode; a clean git state exits 0 '
                             'without scanning anything')
    parser.add_argument('--cache', metavar='DIR',
                        help='content-addressed per-file result cache: '
                             'untouched files are served from DIR instead of '
                             're-analyzed (invalidation contract: the file, '
                             'its sibling .cpp/.cc sources, and the analysis '
                             'package itself — see docs/analysis.md; deleting '
                             'DIR is always safe)')
    parser.add_argument('--rules', action='store_true',
                        help='list the rule families and exit')
    return parser


def main(argv=None):
    from petastorm_tpu.analysis import ALL_CHECKERS, run_analysis
    from petastorm_tpu.analysis.core import load_baseline, write_baseline

    args = build_parser().parse_args(argv)

    if args.rules:
        for cls in ALL_CHECKERS:
            print('{:<7} {:<22} {}'.format(cls.code, cls.name, cls.description))
        return EXIT_CLEAN

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print('error: no such path: {}'.format(p), file=sys.stderr)
            return EXIT_USAGE

    def parse_prefixes(raw, flag):
        if not raw:
            return None
        prefixes = [c.strip().upper() for c in raw.split(',') if c.strip()]
        known = [c for cls in ALL_CHECKERS for c in cls.rule_codes()] + ['PT000']
        for prefix in prefixes:
            if not any(code.startswith(prefix) for code in known):
                print('error: {} prefix {!r} matches no known rule family '
                      '(see --rules)'.format(flag, prefix), file=sys.stderr)
                return EXIT_USAGE
        return prefixes

    select = parse_prefixes(args.select, '--select')
    if select == EXIT_USAGE:
        return EXIT_USAGE
    ignore = parse_prefixes(args.ignore, '--ignore')
    if ignore == EXIT_USAGE:
        return EXIT_USAGE
    baseline = load_baseline(args.baseline) if args.baseline else None
    keep_suppressed = args.format in ('json', 'sarif') and not args.write_baseline
    if args.changed or args.cache:
        from petastorm_tpu.analysis.cache import (ResultCache,
                                                  changed_file_entries,
                                                  iter_file_entries,
                                                  run_analysis_incremental)
        try:
            entries = (changed_file_entries(paths) if args.changed
                       else iter_file_entries(paths))
        except RuntimeError as e:
            print('error: {}'.format(e), file=sys.stderr)
            return EXIT_USAGE
        cache = ResultCache(args.cache) if args.cache else None
        # the whole-program pass (PT13xx) always sees the FULL listing — a
        # changed-files subset cannot support cross-module analysis
        program_entries = iter_file_entries(paths) if args.changed else None
        findings = run_analysis_incremental(
            entries, cache=cache, baseline=baseline, select=select,
            ignore=ignore, keep_suppressed=keep_suppressed,
            program_entries=program_entries)
        if args.changed:
            print('{} changed file{} scanned'.format(
                len(entries), '' if len(entries) == 1 else 's'),
                file=sys.stderr)
        if cache is not None:
            print('cache: {} hit{}, {} miss{}'.format(
                cache.hits, '' if cache.hits == 1 else 's',
                cache.misses, '' if cache.misses == 1 else 'es'),
                file=sys.stderr)
    else:
        findings = run_analysis(paths, baseline=baseline, select=select,
                                ignore=ignore, keep_suppressed=keep_suppressed)
    open_findings = [f for f in findings if f.status == 'open']

    if args.write_baseline:
        write_baseline(args.write_baseline, open_findings)
        print('baseline with {} entr{} written to {}'.format(
            len(open_findings), 'y' if len(open_findings) == 1 else 'ies',
            args.write_baseline))
        return EXIT_CLEAN

    if args.format == 'json':
        # JSONL: one stable finding object per line (see the module docstring
        # for the schema); noqa/baselined findings ride along with their
        # status so machine consumers can annotate suppressions too
        for f in findings:
            print(json.dumps(f.to_dict(), sort_keys=True))
    elif args.format == 'sarif':
        from petastorm_tpu.analysis.sarif import to_sarif
        print(json.dumps(to_sarif(findings, ALL_CHECKERS), indent=2,
                         sort_keys=True))
    else:
        for f in open_findings:
            print(f.format())
            if f.snippet:
                print('    {}'.format(f.snippet))
        print('{} finding{}'.format(len(open_findings),
                                    '' if len(open_findings) == 1 else 's'))
    return EXIT_FINDINGS if open_findings else EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
