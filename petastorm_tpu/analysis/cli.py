"""CLI for the first-party linter.

::

    python -m petastorm_tpu.analysis [paths ...] [options]
    petastorm-tpu-lint [paths ...] [options]

Default path is the installed ``petastorm_tpu`` package. Exit status: 0 when
clean (after noqa + baseline), 1 when findings remain, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _default_target():
    import petastorm_tpu
    return os.path.dirname(os.path.abspath(petastorm_tpu.__file__))


def build_parser():
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-lint',
        description='Repo-specific invariant linter: lock discipline (PT100), '
                    'resource lifecycle (PT200), exception hygiene (PT300), JAX '
                    'purity (PT400), native-buffer safety (PT500), hashability '
                    '(PT600). See docs/analysis.md.')
    parser.add_argument('paths', nargs='*',
                        help='files/directories to scan (default: the installed '
                             'petastorm_tpu package)')
    parser.add_argument('--format', choices=('text', 'json'), default='text')
    parser.add_argument('--baseline', metavar='FILE',
                        help='analysis_baseline.json absorbing known findings '
                             '(missing file = empty baseline)')
    parser.add_argument('--write-baseline', metavar='FILE',
                        help='write the current findings as a baseline and exit 0')
    parser.add_argument('--select', metavar='CODES',
                        help='comma-separated rule-id prefixes to report '
                             '(e.g. PT1,PT500)')
    parser.add_argument('--rules', action='store_true',
                        help='list the rule families and exit')
    return parser


def main(argv=None):
    from petastorm_tpu.analysis import ALL_CHECKERS, run_analysis
    from petastorm_tpu.analysis.core import load_baseline, write_baseline

    args = build_parser().parse_args(argv)

    if args.rules:
        for cls in ALL_CHECKERS:
            print('{:<7} {:<22} {}'.format(cls.code, cls.name, cls.description))
        return 0

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print('error: no such path: {}'.format(p), file=sys.stderr)
            return 2

    select = [c.strip().upper() for c in args.select.split(',')] if args.select else None
    baseline = load_baseline(args.baseline) if args.baseline else None
    findings = run_analysis(paths, baseline=baseline, select=select)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print('baseline with {} entr{} written to {}'.format(
            len(findings), 'y' if len(findings) == 1 else 'ies', args.write_baseline))
        return 0

    if args.format == 'json':
        print(json.dumps({'findings': [f.to_dict() for f in findings],
                          'count': len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.format())
            if f.snippet:
                print('    {}'.format(f.snippet))
        print('{} finding{}'.format(len(findings), '' if len(findings) == 1 else 's'))
    return 1 if findings else 0


if __name__ == '__main__':
    sys.exit(main())
