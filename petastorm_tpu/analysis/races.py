"""PT1300–PT1303 — whole-program race lints over the thread plane.

PT100/PT101 (:mod:`locks`) are deliberately *class-local*: they prove each
class's own lock discipline. But the pipeline's threads cross class and
module boundaries constantly — the autotune tick actuates pool knobs, pools
call back into the ventilator, slots call registry callbacks — and the
defects that survive class-local checking are exactly the cross-cutting
ones. This module builds ONE model over all the concurrency domains
(``workers/``, ``serve/``, ``elastic/``, ``autotune/``, ``chunkstore/``,
``observability/``, ``jax/``, ``shuffling_buffer.py``,
``native/lifetime.py``) and checks four whole-program properties:

**PT1300** cross-class lock-order cycles. Every ``with self._lock`` nesting
and every call made *while holding a lock* contributes edges to a global
lock-order graph; calls are resolved through ``self`` helpers (any depth —
superseding PT101's one-level limit), through attributes with a known
constructor type (``self._pool = ThreadPool(...)``), and — when a method
name is defined by at most a few scoped classes and is not a generic
container verb — by name. A cycle spanning two classes is an ABBA deadlock
no single class can see. Cycles PT101 already reports (single class, one
level of indirection) are deduplicated away: PT101 keeps class-local
cycles, PT1300 owns everything deeper or wider.

**PT1301** guarded reads. An attribute *mutated in place* (``.append``,
``self.d[k] = v``, ...) under a lock is a guarded mutable container;
reading it (iterating, subscripting, passing it on) with no lock held can
observe a torn view mid-mutation. Guarded-by inference follows ``self``
helper calls: a private helper invoked only under ``self._lock`` inherits
that lock for everything in its body (the ``# noqa: PT100 - caller holds
_cv`` convention, computed instead of annotated).

**PT1302** escaping guards. ``return self._items`` hands a caller a live
reference to a lock-guarded container — every use after the lock is
released is un-guarded. Copy out (``list(self._items)``) under the lock
instead.

**PT1303** blocking calls while holding a lock: ``queue.Queue.get/put``
without ``block=False``/``timeout``, ``Event.wait`` without a timeout,
``Condition.wait()`` without a timeout (unbounded — shutdown hangs; the
repo convention is ``wait(timeout=...)`` in a re-check loop), ``join``,
``time.sleep``, and lease/file I/O in ``elastic/`` — each stalls every
other thread that needs the lock for an unbounded time.

Scalar flag writes (``self._stop = True``) are PT100's domain and are
GIL-atomic; PT1301/PT1302 are deliberately restricted to *container
mutation* where a torn multi-step update is physically possible.
"""

from __future__ import annotations

import ast
from collections import defaultdict

from petastorm_tpu.analysis.core import ProgramChecker, attr_chain, class_methods

#: constructors whose result is a lock-like guard (mirrors locks.py)
_LOCK_FACTORIES = {'Lock', 'RLock', 'Condition', 'Semaphore', 'BoundedSemaphore'}
_EVENT_FACTORIES = {'Event'}
_QUEUE_FACTORIES = {'Queue', 'SimpleQueue', 'LifoQueue', 'PriorityQueue',
                    'JoinableQueue'}

#: method calls that mutate their receiver in place (mirrors locks.py)
_MUTATORS = {'append', 'appendleft', 'add', 'clear', 'discard', 'extend',
             'insert', 'pop', 'popitem', 'popleft', 'remove', 'update',
             'setdefault', 'sort', 'reverse'}

#: wrappers that copy a container before it escapes — `return list(self._x)`
_COPY_WRAPPERS = {'list', 'dict', 'tuple', 'set', 'frozenset', 'sorted', 'len',
                  'sum', 'min', 'max', 'any', 'all', 'bool', 'str', 'repr'}

#: method names too generic to resolve by name across classes (container and
#: sync verbs every other type also defines) — resolving `x.get()` to every
#: class with a `get` would invent call edges that do not exist
_GENERIC_METHOD_NAMES = _MUTATORS | {
    'get', 'put', 'read', 'write', 'send', 'recv', 'close', 'open', 'copy',
    'items', 'keys', 'values', 'count', 'index', 'join', 'wait', 'notify',
    'notify_all', 'acquire', 'release', 'start', 'run', 'flush', 'seek',
    'format', 'split', 'strip', 'encode', 'decode', 'info', 'debug',
    'warning', 'error',
}

#: cap on name-based (untyped) resolution fan-out
_MAX_NAME_CANDIDATES = 3

#: call-graph propagation depth for lock-acquisition summaries
_MAX_CALL_DEPTH = 4

#: filesystem calls that are lease I/O when made in elastic/ modules
_FILE_IO_CHAINS = {'os.replace', 'os.fsync', 'os.rename', 'shutil.copy'}


def _self_attr(node):
    """'attr' when node is ``self.attr``, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == 'self':
        return node.attr
    return None


def _ctor_name(value):
    """Class/type name constructed by ``value`` when it is a call like
    ``ClassName(...)`` / ``mod.ClassName(...)``, else None."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _Scan(ast.NodeVisitor):
    """One pass over a method body: held-lock stack, reads/writes of ``self``
    attributes, calls (with enough receiver structure to resolve them), escape
    sites, and blocking calls."""

    def __init__(self, model):
        self.model = model
        self.held = []           # stack of held lock attr names
        self.acquired = set()    # every lock attr this method acquires
        self.writes = []         # (attr, frozenset(held), lineno, is_mutation)
        self.reads = []          # (attr, frozenset(held), lineno)
        self.calls = []          # (kind, recv, mname, frozenset(held), lineno)
        self.escapes = []        # (attr, frozenset(held), lineno, verb)
        self.blockers = []       # (kind, desc, frozenset(held), lineno)
        self.with_edges = []     # (outer, inner, lineno)
        self._skip = set()       # node ids consumed by a surrounding construct

    # -- lock acquisition ---------------------------------------------------

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.model.lock_attrs:
                acquired.append(attr)
                self._skip.add(id(item.context_expr))
        if acquired:
            self.acquired.update(acquired)
            for outer in self.held:
                for inner in acquired:
                    if outer != inner:
                        self.with_edges.append((outer, inner, node.lineno))
        self.held.extend(acquired)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    # -- writes -------------------------------------------------------------

    def _record_write(self, target, lineno):
        attr = _self_attr(target)
        is_mutation = False
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)   # self.d[k] = v mutates self.d
            if attr is not None:
                is_mutation = True
                self._skip.add(id(target.value))
        if attr is not None and attr not in self.model.lock_attrs:
            self.writes.append((attr, frozenset(self.held), lineno, is_mutation))

    def visit_Assign(self, node):
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]):
                self._record_write(el, node.lineno)
        self._record_store_escape(node)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._record_write(node.target, node.lineno)
        if isinstance(node.target, ast.Subscript):
            self.visit(node.target.value)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record_write(node.target, node.lineno)
            self.visit(node.value)

    def _record_store_escape(self, node):
        """``other.x = self._items`` / ``d[k] = self._items`` stores a live
        reference into foreign state (PT1302 'store' flavor). ``self.x =
        self._y`` aliasing stays in-class and is not an escape."""
        values = (node.value.elts if isinstance(node.value, (ast.Tuple, ast.List))
                  else [node.value])
        stored = [a for a in (_self_attr(v) for v in values) if a is not None]
        if not stored:
            return
        for t in node.targets:
            base = None
            if isinstance(t, ast.Attribute):
                base = t.value
            elif isinstance(t, ast.Subscript):
                base = t.value
            if base is None or _self_attr(t) is not None:
                continue
            if isinstance(base, ast.Name) and base.id == 'self':
                continue
            for attr in stored:
                self.escapes.append((attr, frozenset(self.held), node.lineno,
                                     'stored into foreign state'))

    # -- escapes ------------------------------------------------------------

    def _escaping_attrs(self, value):
        """Bare ``self.attr`` references escaping via return/yield (tuples
        included; copy wrappers like ``list(...)`` do not escape)."""
        if value is None:
            return []
        values = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                  else [value])
        out = []
        for v in values:
            attr = _self_attr(v)
            if attr is not None and attr not in self.model.lock_attrs:
                out.append((attr, v))
        return out

    def visit_Return(self, node):
        for attr, v in self._escaping_attrs(node.value):
            self.escapes.append((attr, frozenset(self.held), node.lineno,
                                 'returned'))
            self._skip.add(id(v))
        if node.value is not None:
            self.visit(node.value)

    def visit_Yield(self, node):
        for attr, v in self._escaping_attrs(node.value):
            self.escapes.append((attr, frozenset(self.held), node.lineno,
                                 'yielded'))
            self._skip.add(id(v))
        if node.value is not None:
            self.visit(node.value)

    # -- calls / blockers ---------------------------------------------------

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            self._scan_method_call(node, func)
        elif isinstance(func, ast.Name) and func.id in _COPY_WRAPPERS:
            # `list(self._x)` copies — the attr read inside is still a read
            pass
        self.generic_visit(node)

    def _scan_method_call(self, node, func):
        mname = func.attr
        recv = func.value
        recv_attr = _self_attr(recv)
        held = frozenset(self.held)
        kwnames = {kw.arg for kw in node.keywords}

        # receiver bookkeeping --------------------------------------------
        if recv_attr is not None and mname in _MUTATORS \
                and recv_attr not in self.model.lock_attrs:
            self.writes.append((recv_attr, held, node.lineno, True))
            self._skip.add(id(recv))
        if _self_attr(func) is not None:
            # `self.m(...)` — a method fetch, not a state read
            self.calls.append(('self', None, mname, held, node.lineno))
            return
        if recv_attr is not None:
            self.calls.append(('attr', recv_attr, mname, held, node.lineno))
        elif isinstance(recv, ast.Name):
            self.calls.append(('var', recv.id, mname, held, node.lineno))

        # blocking-call detection -----------------------------------------
        has_timeout = 'timeout' in kwnames
        if mname == 'join':
            pos = node.args
            timeout_like = ((not pos and kwnames <= {'timeout'}) or
                            (len(pos) == 1 and not kwnames and
                             isinstance(pos[0], ast.Constant) and
                             isinstance(pos[0].value, (int, float))))
            if timeout_like:
                self.blockers.append(('join', '{}.join()'.format(
                    attr_chain(recv) or '<expr>'), held, node.lineno))
        elif mname == 'wait' and not node.args and not has_timeout:
            if recv_attr in self.model.lock_attrs:
                self.blockers.append(('cond-wait', 'self.{}.wait() without a '
                                      'timeout'.format(recv_attr), held,
                                      node.lineno))
            elif recv_attr in self.model.event_attrs:
                self.blockers.append(('event-wait', 'self.{}.wait() without a '
                                      'timeout'.format(recv_attr), held,
                                      node.lineno))
        elif recv_attr in self.model.queue_attrs:
            blocking = False
            if mname == 'get':
                blocking = not node.args and not has_timeout \
                    and 'block' not in kwnames
            elif mname == 'put':
                blocking = len(node.args) <= 1 and not has_timeout \
                    and 'block' not in kwnames
            if blocking:
                self.blockers.append(('queue', 'blocking self.{}.{}()'.format(
                    recv_attr, mname), held, node.lineno))
        else:
            chain = attr_chain(func)
            if chain == 'time.sleep':
                self.blockers.append(('sleep', 'time.sleep()', held,
                                      node.lineno))
            elif chain in _FILE_IO_CHAINS:
                self.blockers.append(('io', chain + '()', held, node.lineno))

    # -- reads --------------------------------------------------------------

    def visit_Attribute(self, node):
        if id(node) not in self._skip and isinstance(node.ctx, ast.Load):
            attr = _self_attr(node)
            if attr is not None and attr not in self.model.lock_attrs \
                    and attr not in self.model.event_attrs \
                    and attr not in self.model.queue_attrs:
                self.reads.append((attr, frozenset(self.held), node.lineno))
        self.generic_visit(node)

    # nested defs/lambdas run later, possibly on another thread or lock
    # context — their accesses are not attributable to the current held set
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return


class _ClassModel(object):
    """Per-class facts: lock/event/queue attributes, attribute constructor
    types, and one :class:`_Scan` per directly-defined method."""

    def __init__(self, src, classdef):
        self.src = src
        self.name = classdef.name
        self.lineno = classdef.lineno
        methods = class_methods(classdef)
        self.lock_attrs = set()
        self.event_attrs = set()
        self.queue_attrs = set()
        self.attr_types = {}
        for m in methods:
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                ctor = _ctor_name(node.value)
                if ctor is None:
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if ctor in _LOCK_FACTORIES:
                        self.lock_attrs.add(attr)
                    elif ctor in _EVENT_FACTORIES:
                        self.event_attrs.add(attr)
                    elif ctor in _QUEUE_FACTORIES:
                        self.queue_attrs.add(attr)
                    elif ctor[:1].isupper():
                        self.attr_types[attr] = ctor
        self.scans = {}
        self.method_linenos = {}
        for m in methods:
            scan = _Scan(self)
            for stmt in m.body:
                scan.visit(stmt)
            self.scans[m.name] = scan
            self.method_linenos[m.name] = m.lineno
        self.ambient = self._infer_ambient()

    def _infer_ambient(self):
        """Locks held at EVERY internal call site of each private helper —
        the computed version of the tree's ``# noqa: PT100 - caller holds
        _cv`` annotations. Public methods (callable from outside the class)
        and helpers ever called lock-free get the empty set."""
        sites = defaultdict(list)
        for caller, scan in self.scans.items():
            for kind, _recv, mname, held, _lineno in scan.calls:
                if kind == 'self' and mname in self.scans:
                    sites[mname].append((caller, held))
        ambient = {mn: frozenset() for mn in self.scans}
        for _ in range(_MAX_CALL_DEPTH):
            nxt = {}
            for mn in self.scans:
                private = mn.startswith('_') and not mn.startswith('__')
                if not private or mn not in sites:
                    nxt[mn] = frozenset()
                    continue
                inter = None
                for caller, held in sites[mn]:
                    eff = held | ambient.get(caller, frozenset())
                    inter = eff if inter is None else (inter & eff)
                nxt[mn] = inter or frozenset()
            if nxt == ambient:
                break
            ambient = nxt
        return ambient

    def effective_held(self, method, held):
        return held | self.ambient.get(method, frozenset())


class RaceChecker(ProgramChecker):
    code = 'PT1300'
    codes = ('PT1300', 'PT1301', 'PT1302', 'PT1303')
    name = 'thread-races'
    description = ('whole-program lock-order cycles (PT1300), unguarded reads '
                   'of lock-guarded containers (PT1301), guarded containers '
                   'escaping their lock (PT1302), blocking calls under a lock '
                   '(PT1303)')
    scope = ('*workers/*.py', '*serve/*.py', '*elastic/*.py', '*autotune/*.py',
             '*chunkstore/*.py', '*observability/*.py', '*jax/*.py',
             '*fabric/*.py', '*shuffling_buffer.py', '*native/lifetime.py')

    def check_program(self, sources):
        models = []
        for src in sources:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    models.append(_ClassModel(src, node))
        class_index = {}
        for m in models:
            class_index.setdefault(m.name, m)
        method_index = defaultdict(list)
        for m in models:
            for mn in m.scans:
                method_index[mn].append(m)

        for model in models:
            guarded = self._guarded_containers(model)
            yield from self._check_guarded_reads(model, guarded)
            yield from self._check_escapes(model, guarded)
            yield from self._check_blocking(model)
        yield from self._check_lock_order(models, class_index, method_index)

    # -- PT1301 / PT1302 ----------------------------------------------------

    @staticmethod
    def _guarded_containers(model):
        """attr -> set of guarding locks, for attrs mutated in place under a
        lock (outside __init__ — no second thread exists during __init__)."""
        guarded = defaultdict(set)
        for mn, scan in model.scans.items():
            if mn == '__init__':
                continue
            for attr, held, _lineno, is_mut in scan.writes:
                eff = model.effective_held(mn, held)
                if is_mut and eff:
                    guarded[attr] |= eff
        return guarded

    def _check_guarded_reads(self, model, guarded):
        for mn, scan in model.scans.items():
            if mn == '__init__':
                continue
            flagged = set()
            for attr, held, lineno in scan.reads:
                if attr not in guarded:
                    continue
                if model.effective_held(mn, held):
                    continue
                if (attr, lineno) in flagged:
                    continue
                flagged.add((attr, lineno))
                yield self.finding(
                    model.src, lineno,
                    "read of lock-guarded container 'self.{}' with no lock "
                    'held (guarded by {} in class {}) — a concurrent mutation '
                    'tears the view'.format(
                        attr,
                        ' / '.join("'self.{}'".format(a)
                                   for a in sorted(guarded[attr])),
                        model.name),
                    code='PT1301')

    def _check_escapes(self, model, guarded):
        for mn, scan in model.scans.items():
            for attr, _held, lineno, verb in scan.escapes:
                if attr not in guarded:
                    continue
                yield self.finding(
                    model.src, lineno,
                    "lock-guarded container 'self.{}' {} as a live reference "
                    '(guarded by {} in class {}) — callers touch it after the '
                    'lock is released; copy out under the lock instead'.format(
                        attr, verb,
                        ' / '.join("'self.{}'".format(a)
                                   for a in sorted(guarded[attr])),
                        model.name),
                    code='PT1302')

    # -- PT1303 -------------------------------------------------------------

    def _check_blocking(self, model):
        in_elastic = '/elastic/' in ('/' + model.src.relpath)
        for mn, scan in model.scans.items():
            for kind, desc, held, lineno in scan.blockers:
                eff = model.effective_held(mn, held)
                if kind == 'cond-wait':
                    # unbounded Condition.wait is flagged even though wait()
                    # releases its own lock: there is no bound on the stall and
                    # shutdown paths hang (tree convention: wait(timeout=...)
                    # inside a re-check loop)
                    yield self.finding(
                        model.src, lineno,
                        'unbounded {} (class {}) — wait(timeout=...) in a '
                        're-check loop is the shutdown-safe form'.format(
                            desc, model.name),
                        code='PT1303')
                    continue
                if kind == 'io' and not in_elastic:
                    continue
                if eff:
                    yield self.finding(
                        model.src, lineno,
                        '{} while holding {} (class {}) — every thread '
                        'needing the lock stalls for an unbounded time'.format(
                            desc,
                            ' / '.join("'self.{}'".format(a)
                                       for a in sorted(eff)),
                            model.name),
                        code='PT1303')

    # -- PT1300 -------------------------------------------------------------

    def _resolve_call(self, model, kind, recv, mname, class_index, method_index):
        """Possible (model, method) targets of one call site.

        Resolution order: exact (``self`` method / constructor-typed attr),
        then unique method name, then — only when the receiver's name
        correlates with the candidate class name (``self._pool`` vs
        ``ProcessPool``) — ambiguous names with a small candidate set.
        Uncorrelated ambiguous receivers resolve to nothing: inventing call
        edges (``tq.stats()`` -> every class with a ``stats``) would report
        deadlock cycles that cannot execute."""
        if kind == 'self':
            if mname in model.scans:
                return [(model, mname)]
            return []
        if kind == 'attr':
            tname = model.attr_types.get(recv)
            if tname and tname in class_index \
                    and mname in class_index[tname].scans:
                return [(class_index[tname], mname)]
        if mname in _GENERIC_METHOD_NAMES or mname.startswith('__'):
            return []
        cands = [m for m in method_index.get(mname, ())]
        if not cands or len(cands) > _MAX_NAME_CANDIDATES:
            return []
        if len(cands) == 1:
            return [(cands[0], mname)]
        tokens = [t for t in (recv or '').strip('_').lower().split('_')
                  if len(t) >= 3]
        return [(m, mname) for m in cands
                if any(t in m.name.lower() for t in tokens)]

    def _acq_summary(self, model, mname, class_index, method_index, memo,
                     stack=()):
        """{(class, lock): min call depth} of every lock the method may
        acquire, following resolved calls up to ``_MAX_CALL_DEPTH``."""
        key = (id(model), mname)
        if key in memo:
            return memo[key]
        memo[key] = {}                      # cycle guard during computation
        scan = model.scans[mname]
        out = {}
        for lock in scan.acquired:
            out[(model.name, lock)] = 1
        for kind, recv, cm, _held, _lineno in scan.calls:
            for tmodel, tmn in self._resolve_call(model, kind, recv, cm,
                                                 class_index, method_index):
                tkey = (id(tmodel), tmn)
                if tkey in stack:
                    continue
                sub = self._acq_summary(tmodel, tmn, class_index, method_index,
                                        memo, stack + (key,))
                for node, depth in sub.items():
                    if depth + 1 <= _MAX_CALL_DEPTH:
                        cur = out.get(node)
                        if cur is None or depth + 1 < cur:
                            out[node] = depth + 1
        memo[key] = out
        return out

    def _check_lock_order(self, models, class_index, method_index):
        edges = defaultdict(set)     # (cls, lock) -> {(cls, lock)}
        edge_info = {}               # (u, v) -> (src, lineno, pt101_visible)
        memo = {}
        for model in models:
            for mn, scan in model.scans.items():
                for outer, inner, lineno in scan.with_edges:
                    u, v = (model.name, outer), (model.name, inner)
                    edges[u].add(v)
                    edge_info.setdefault((u, v), (model.src, lineno, True))
                for kind, recv, cm, held, lineno in scan.calls:
                    eff = model.effective_held(mn, held)
                    if not eff:
                        continue
                    targets = self._resolve_call(model, kind, recv, cm,
                                                 class_index, method_index)
                    for tmodel, tmn in targets:
                        summary = self._acq_summary(tmodel, tmn, class_index,
                                                    method_index, memo)
                        for node, depth in summary.items():
                            for h in sorted(eff):
                                u = (model.name, h)
                                if u == node:
                                    continue
                                edges[u].add(node)
                                # PT101 sees: same class, direct self call,
                                # callee acquires the lock itself, and the
                                # outer lock is syntactically held (not
                                # ambient-inferred)
                                visible = (kind == 'self'
                                           and node[0] == model.name
                                           and depth == 1 and h in held)
                                prev = edge_info.get((u, node))
                                if prev is None or (visible and not prev[2]):
                                    edge_info[(u, node)] = (model.src, lineno,
                                                            visible)
        for cycle in _find_cycles(edges):
            cycle_classes = {cls for cls, _lock in cycle}
            cycle_edges = list(zip(cycle, cycle[1:] + (cycle[0],)))
            if len(cycle_classes) == 1 \
                    and all(edge_info[e][2] for e in cycle_edges):
                continue                      # PT101's class-local territory
            src, lineno, _vis = edge_info[cycle_edges[0]]
            names = ['{}.{}'.format(cls, lock) for cls, lock in cycle]
            names.append(names[0])
            yield self.finding(
                src, lineno,
                'cross-module lock-acquisition-order cycle {} — two threads '
                'entering from different edges deadlock (call-graph edges '
                'included; see docs/analysis.md PT1300)'.format(
                    ' -> '.join("'{}'".format(n) for n in names)),
                code='PT1300')


def _find_cycles(edges):
    """Minimal distinct cycles of a small digraph, as node tuples (rotation-
    deduplicated, deterministic order)."""
    cycles = []
    seen_cycles = set()

    def dfs(start, node, path):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                canon = tuple(path)
                rotations = {canon[i:] + canon[:i] for i in range(len(canon))}
                if not rotations & seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(canon)
            elif nxt not in path and nxt > start:
                dfs(start, nxt, path + [nxt])

    for start in sorted(edges):
        dfs(start, start, [start])
    return cycles
