"""``python -m petastorm_tpu.analysis`` entry point."""

import sys

from petastorm_tpu.analysis.cli import main

if __name__ == '__main__':
    sys.exit(main())
