"""PT800/PT801 — worker-pool protocol lints.

The supervision protocol's correctness argument (``docs/protocol.md``) leans
on two source-level disciplines the model checker and runtime monitor cannot
see:

* **PT800 — exhaustive message-kind dispatch.** A consumer switch over the
  results-channel kind bytes (``if kind == MSG_DATA: ... elif ...``) that
  misses a declared kind silently drops that message class — the historical
  failure mode of hand-rolled ``if msg[0] == ...`` chains (a dropped
  ``MSG_METRICS`` loses telemetry; a dropped ``MSG_DONE`` wedges the epoch).
  Every dispatch chain comparing a common subject against two or more kind
  constants must either cover ALL kinds declared in
  ``workers/protocol.MESSAGE_KINDS`` or carry an explicit ``else`` default.
* **PT801 — canonical protocol constants.** ``workers/protocol.py`` is the
  single definition site for message-kind bytes, the control sentinel and the
  ring framing. A second definition (``_DATA = b'D'`` in a pool module, or a
  raw kind byte literal in a comparison) re-opens the drift the 2024-era
  petastorm forks suffered, where two modules disagreed about one byte.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker
from petastorm_tpu.workers.protocol import KIND_CONSTANT_NAMES, MESSAGE_KINDS

#: canonical kind name (e.g. 'DATA') per recognized constant identifier:
#: the MSG_* names plus the legacy underscore spellings
_KIND_BY_IDENT = {}
for _name, _byte in KIND_CONSTANT_NAMES.items():
    _KIND_BY_IDENT[_name] = _name[len('MSG_'):]
    _KIND_BY_IDENT['_' + _name[len('MSG_'):]] = _name[len('MSG_'):]

_ALL_KIND_NAMES = frozenset(_KIND_BY_IDENT.values())

#: the reserved wire bytes (kind bytes + the control sentinel)
_RESERVED_BYTES = frozenset(MESSAGE_KINDS) | {b'FINISHED'}

#: identifiers PT801 treats as protocol-constant definitions
_PROTOCOL_IDENTS = frozenset(_KIND_BY_IDENT) | {
    'CONTROL_FINISHED', '_CONTROL_FINISHED', 'RING_HEADER_LEN'}

_CANONICAL_MODULE = 'workers/protocol.py'


def _ident_of(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _kind_names_in_test(test):
    """Canonical kind names a branch test handles, plus the comparison subject
    (unparsed) — or (None, ()) when the test is not a kind comparison.
    Understands ``x == K``, ``x == K1 or x == K2``, and ``x in (K1, K2)``."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        subject = None
        names = []
        for value in test.values:
            s, n = _kind_names_in_test(value)
            if s is None:
                return None, ()
            if subject is None:
                subject = s
            elif s != subject:
                return None, ()
            names.extend(n)
        return subject, tuple(names)
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None, ()
    op = test.ops[0]
    comparator = test.comparators[0]
    if isinstance(op, ast.Eq):
        candidates = [comparator]
    elif isinstance(op, ast.In) and isinstance(comparator, (ast.Tuple, ast.List, ast.Set)):
        candidates = list(comparator.elts)
    else:
        return None, ()
    names = []
    for cand in candidates:
        kind = _KIND_BY_IDENT.get(_ident_of(cand) or '')
        if kind is None:
            return None, ()
        names.append(kind)
    return ast.unparse(test.left), tuple(names)


class ProtocolLintChecker(Checker):
    """PT800 (non-exhaustive kind dispatch) + PT801 (protocol constants
    defined outside ``workers/protocol.py``)."""

    code = 'PT800'
    codes = ('PT800', 'PT801')
    name = 'protocol-discipline'
    description = ('message-kind dispatch chains must cover every declared kind '
                   'or carry an else (PT800); protocol constants/bytes are '
                   'defined only in workers/protocol.py (PT801)')
    scope = ('*workers/*.py',)

    def _is_canonical_module(self, src):
        return src.relpath.endswith('protocol.py')

    def check(self, src):
        yield from self._check_dispatch_chains(src)
        if not self._is_canonical_module(src):
            yield from self._check_definition_site(src)

    # -- PT800 ---------------------------------------------------------------

    def _chain_heads(self, tree):
        """Top ``ast.If`` nodes of elif chains (an If that is some other If's
        sole orelse member is a link, not a head)."""
        links = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.If) and len(node.orelse) == 1 \
                    and isinstance(node.orelse[0], ast.If):
                links.add(id(node.orelse[0]))
        return [n for n in ast.walk(tree)
                if isinstance(n, ast.If) and id(n) not in links]

    def _check_dispatch_chains(self, src):
        for head in self._chain_heads(src.tree):
            node = head
            subject = None
            handled = []
            branches = 0
            has_default = False
            while True:
                s, names = _kind_names_in_test(node.test)
                if s is not None and (subject is None or s == subject):
                    subject = s
                    handled.extend(names)
                    branches += 1
                orelse = node.orelse
                if len(orelse) == 1 and isinstance(orelse[0], ast.If):
                    node = orelse[0]
                    continue
                has_default = bool(orelse)
                break
            if branches < 2:
                continue  # one comparison is a guard, not a dispatch
            missing = sorted(_ALL_KIND_NAMES - set(handled))
            if missing and not has_default:
                yield self.finding(
                    src, head.lineno,
                    'message-kind dispatch on {!r} misses declared kind(s) {} '
                    'and has no else — a message of a missing kind is silently '
                    'dropped; handle every workers/protocol.MESSAGE_KINDS entry '
                    'or add an explicit default'.format(subject, ', '.join(missing)),
                    code='PT800')

    # -- PT801 ---------------------------------------------------------------

    def _check_definition_site(self, src):
        imported = self._imported_protocol_names(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for target in targets:
                    elts = target.elts if isinstance(target, ast.Tuple) else [target]
                    for el in elts:
                        name = el.id if isinstance(el, ast.Name) else None
                        if name in _PROTOCOL_IDENTS and name not in imported:
                            yield self.finding(
                                src, node.lineno,
                                'protocol constant {!r} defined outside the '
                                'canonical module — import it from '
                                'petastorm_tpu.{} instead'.format(
                                    name, _CANONICAL_MODULE.replace('/', '.')[:-3]),
                                code='PT801')
            elif isinstance(node, ast.Compare):
                for comp in node.comparators:
                    if isinstance(comp, ast.Constant) and comp.value in _RESERVED_BYTES:
                        yield self.finding(
                            src, node.lineno,
                            'raw protocol byte {!r} in a comparison — use the '
                            'named constant from petastorm_tpu.{}'.format(
                                comp.value, _CANONICAL_MODULE.replace('/', '.')[:-3]),
                            code='PT801')

    @staticmethod
    def _imported_protocol_names(tree):
        """Names bound by ``from ...protocol import ...`` — rebinding an
        imported canonical name (e.g. an alias line) is not a definition."""
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.endswith('protocol'):
                names.update(alias.asname or alias.name for alias in node.names)
        return names
