"""PT1500 — fabric socket operations must be timeout-armed and deadline-bound.

The chunk fabric's failure contract (``docs/fabric.md``) rests on two
lexically checkable disciplines in ``petastorm_tpu/fabric/``:

* **explicit per-operation timeouts** — a blocking socket call with no
  timeout turns one stalled peer into a wedged reader thread; every function
  that touches a socket primitive must either arm ``settimeout`` itself or
  receive the armed socket alongside a ``deadline`` parameter (the protocol
  helpers' shape: they re-arm the timeout from the deadline before every
  partial send/recv);
* **an end-to-end deadline context** — per-operation timeouts alone let N
  slow-but-not-stalled operations stack their budgets, so every data-moving
  socket primitive (everything but ``accept``) must run under a
  :class:`~petastorm_tpu.fabric.protocol.Deadline`: either the function
  takes one as a parameter or it constructs one.

``accept`` is exempt from the deadline requirement — the accept loop is a
poll, not a transfer — but still needs its timeout (an un-armed ``accept``
cannot notice ``stop()``).
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, walk_functions

#: blocking socket primitives the rule recognizes (attribute-call tails)
_SOCKET_OPS = frozenset({'connect', 'recv', 'recv_into', 'recvfrom', 'send',
                         'sendall', 'sendto', 'accept'})

#: ops that move transfer data and therefore need the deadline context too
_DATA_OPS = _SOCKET_OPS - {'accept'}


def _socket_op_calls(func):
    """Every ``<expr>.<op>(...)`` call in ``func`` whose op is a blocking
    socket primitive, as (op name, call node) pairs."""
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _SOCKET_OPS:
                yield node.func.attr, node


def _param_names(func):
    args = func.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return frozenset(names)


def _arms_timeout(func):
    """Does ``func`` call ``.settimeout(...)`` anywhere?"""
    return any(isinstance(node, ast.Call)
               and isinstance(node.func, ast.Attribute)
               and node.func.attr == 'settimeout'
               for node in ast.walk(func))


def _builds_deadline(func):
    """Does ``func`` construct a Deadline (``Deadline(...)`` or
    ``P.Deadline(...)``)?"""
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == 'Deadline':
            return True
        if isinstance(f, ast.Attribute) and f.attr == 'Deadline':
            return True
    return False


class FabricSocketChecker(Checker):
    code = 'PT1500'
    name = 'fabric-socket-discipline'
    description = ('socket operations in fabric/ must carry an explicit '
                   'per-operation timeout and run under an end-to-end '
                   'Deadline budget: an un-armed blocking call turns one '
                   'stalled peer into a wedged reader')
    scope = ('*fabric/*.py',)

    def check(self, src):
        for func, _cls in walk_functions(src.tree):
            ops = list(_socket_op_calls(func))
            if not ops:
                continue
            params = _param_names(func)
            has_deadline = ('deadline' in params) or _builds_deadline(func)
            armed = _arms_timeout(func) or 'deadline' in params
            for op, call in ops:
                if not armed:
                    yield self.finding(
                        src, call.lineno,
                        '.{}() in a function that neither arms settimeout '
                        'nor receives a deadline: a stalled peer blocks this '
                        'call forever — arm the socket or take the transfer '
                        'deadline as a parameter'.format(op))
                elif op in _DATA_OPS and not has_deadline:
                    yield self.finding(
                        src, call.lineno,
                        '.{}() outside a deadline context: per-operation '
                        'timeouts stack without an end-to-end budget — take '
                        'a deadline parameter or construct a protocol.'
                        'Deadline in this function'.format(op))
