"""PT900/PT901/PT902 — cross-language ABI conformance at the native boundary.

The fastest paths in the framework are the ones the type system cannot see:
``pstpu_read_fused`` and the shm-ring in-place mode are C structs and
``extern "C"`` signatures in ``native/*.cpp`` mirrored *by hand* as ctypes
layouts and ``argtypes``/``restype`` declarations in ``native/*.py``. Both
memory-safety bugs shipped since the fused kernel landed (the
multiplication-overflow bounds checks, the ``aux_bufs`` index misalignment)
were exactly this class of silent cross-language drift, caught by review
rather than tooling. This checker makes the drift mechanical:

**PT900 — struct-layout drift.** Every ``ctypes.Structure`` whose docstring
declares it a "mirror of ``struct X``" is checked field-for-field
against ``struct X`` parsed out of the sibling ``native/*.cpp`` sources:
the C field offsets and sizes are computed under C layout rules (natural
alignment, padding) and must be identical — same names, same order, same
offset, same size, same kind (pointer / signed / unsigned / float / bytes).
A reordered field, a widened type, or a field added on one side only is a
finding. The ``pstpu_abi_version()`` C literal must equal the Python
``EXPECTED_ABI`` literal (the version gate is itself checked, not trusted).

**PT901 — function-signature drift.** Every ``lib.NAME.argtypes = [...]`` /
``lib.NAME.restype = ...`` declaration is checked against the ``extern "C"``
definition of ``NAME``: argument count must match, each C scalar must map to
a ctypes type of the same size and signedness class, each C pointer must map
to a pointer ctype (``c_void_p``/``c_char_p``/``POINTER(...)``) — and a
pointer to a mirrored struct must map to ``POINTER(<its mirror>)`` or
``c_void_p``. A non-``int`` return type must have an explicit compatible
``restype`` (ctypes' silent default truncates a 64-bit return to 32 bits).

**PT902 — pointer parameter without a traveling capacity bound.** Every
``extern "C"`` function taking a buffer pointer must also take a
capacity/length parameter (the generalization of PT503 from fused
descriptors to the whole call surface): the kernel can only bounds-check
what the caller hands it. NUL-terminated ``const char*`` strings and opaque
``void*`` handles (named ``h``/``*handle``) are exempt.

Suppress a single finding with ``# noqa: PT90x`` (Python) or
``// noqa: PT90x`` (C++) on its line. See ``docs/analysis.md`` — "the ABI is
checked, not trusted".
"""

from __future__ import annotations

import ast
import glob
import os
import re

from petastorm_tpu.analysis.buffers import _strip_cpp_comments_and_strings
from petastorm_tpu.analysis.core import Checker, attr_chain

#: docstring marker binding a ctypes.Structure to the C struct it mirrors
_MIRROR_RE = re.compile(r'mirror of\s+`*struct\s+(\w+)`*')

#: the C ABI version literal (rowgroup_reader.cpp)
_ABI_VERSION_RE = re.compile(
    r'\bpstpu_abi_version\s*\(\s*(?:void)?\s*\)\s*\{\s*return\s+(\d+)\s*;')

# -- C type model -----------------------------------------------------------

#: C scalar type -> (size, kind); kind in int/uint/float (LP64 Linux targets,
#: the only ABI the native kernels build for)
_C_SCALARS = {
    'bool': (1, 'uint'), 'char': (1, 'bytes'), 'signed char': (1, 'int'),
    'unsigned char': (1, 'uint'), 'int8_t': (1, 'int'), 'uint8_t': (1, 'uint'),
    'short': (2, 'int'), 'unsigned short': (2, 'uint'),
    'int16_t': (2, 'int'), 'uint16_t': (2, 'uint'),
    'int': (4, 'int'), 'unsigned': (4, 'uint'), 'unsigned int': (4, 'uint'),
    'int32_t': (4, 'int'), 'uint32_t': (4, 'uint'), 'float': (4, 'float'),
    'long': (8, 'int'), 'unsigned long': (8, 'uint'),
    'long long': (8, 'int'), 'unsigned long long': (8, 'uint'),
    'int64_t': (8, 'int'), 'uint64_t': (8, 'uint'), 'size_t': (8, 'uint'),
    'ssize_t': (8, 'int'), 'off_t': (8, 'int'), 'double': (8, 'float'),
    'png_size_t': (8, 'uint'),
}

_POINTER_SIZE = 8


class CField(object):
    """One parsed C struct field."""

    __slots__ = ('name', 'ctype', 'count', 'offset', 'size', 'kind')

    def __init__(self, name, ctype, count):
        self.name = name
        self.ctype = ctype
        self.count = count  # None for scalars, int for arrays
        self.offset = self.size = 0
        self.kind = 'int'


class CFunc(object):
    """One parsed ``extern "C"`` function definition."""

    __slots__ = ('name', 'ret', 'params', 'lineno')

    def __init__(self, name, ret, params, lineno):
        self.name = name
        self.ret = ret          # normalized C type string
        self.params = params    # [(normalized type, name)]
        self.lineno = lineno


def _normalize_ctype(raw):
    """Canonical C type string: const/struct/volatile stripped, ``std::atomic<T>``
    unwrapped, pointer stars separated (``'uint8_t *'``/``'void * *'``)."""
    t = raw.strip()
    t = re.sub(r'\bstd::atomic\s*<\s*([^>]+?)\s*>', r'\1', t)
    t = re.sub(r'\b(const|volatile|struct|restrict)\b', ' ', t)
    stars = t.count('*')
    t = t.replace('*', ' ')
    t = ' '.join(t.split())
    return t + ' *' * stars


def _is_pointer(ctype):
    return ctype.endswith('*')


def _scalar_info(ctype):
    """(size, kind) of a normalized scalar C type, or None when unknown."""
    return _C_SCALARS.get(ctype)


def _eval_array_count(expr):
    """Evaluate a constant array-size expression (digits, + - * / ( ), and
    ``sizeof(type)``); None when it isn't that simple."""
    def sizeof_sub(m):
        info = _scalar_info(_normalize_ctype(m.group(1)))
        if info is None:
            return 'X'  # poison: unknown type makes the eval fail below
        return str(info[0])

    expr = re.sub(r'sizeof\s*\(\s*([^)]+?)\s*\)', sizeof_sub, expr)
    if not re.fullmatch(r'[0-9+\-*/() ]+', expr):
        return None
    try:
        value = eval(expr, {'__builtins__': {}})  # noqa: S307 - digits/ops only, checked above
    except Exception:  # noqa: BLE001 - malformed constant: caller skips the struct
        return None
    return int(value) if isinstance(value, (int, float)) and value == int(value) else None


_FIELD_RE = re.compile(
    r'^(?P<type>[\w:<>\s]+?(?:\s*\*+)?)\s*(?P<name>\w+)\s*'
    r'(?:\[(?P<count>[^\]]+)\])?$')


def parse_cpp_structs(text):
    """``{name: [CField]}`` for every ``struct NAME { ... };`` whose body
    parses as plain data fields; structs with methods/initializers simply
    yield the fields that do parse (a mirror check against one fails loudly
    on the count mismatch, never silently passes)."""
    structs = {}
    for m in re.finditer(r'\bstruct\s+(\w+)\s*\{', text):
        name = m.group(1)
        open_idx = text.index('{', m.end() - 1)
        end = _match_brace(text, open_idx)
        if end is None:
            continue
        body = text[open_idx + 1:end]
        fields = []
        for decl in body.split(';'):
            decl = ' '.join(decl.split())
            # parens inside [..] are array-size arithmetic (sizeof), not a
            # method signature — judge "is this a method?" outside brackets
            outside = re.sub(r'\[[^\]]*\]', '[]', decl)
            if not decl or '(' in outside or '{' in decl or '}' in decl:
                continue  # methods, nested types, default-init expressions
            decl = decl.split('=')[0].strip()  # strip default member init
            declarators = [p.strip() for p in decl.split(',')]
            fm = _FIELD_RE.match(declarators[0])
            if not fm:
                continue
            ctype = _normalize_ctype(fm.group('type'))
            entries = [(fm.group('name'), fm.group('count'))]
            for extra in declarators[1:]:
                # C attaches '*'/[n] to the declarator, not the type — plain
                # additional names share the base type, anything fancier bails
                em = re.match(r'^(?P<name>\w+)\s*(?:\[(?P<count>[^\]]+)\])?$',
                              extra)
                if not em:
                    entries = None
                    break
                entries.append((em.group('name'), em.group('count')))
            if entries is None:
                continue
            for fname, raw_count in entries:
                count = None
                if raw_count is not None:
                    count = _eval_array_count(raw_count)
                    if count is None:
                        break
                fields.append(CField(fname, ctype, count))
        structs[name] = fields
    return structs


def layout_struct(fields):
    """Assign offset/size/kind to ``fields`` under C layout rules (natural
    alignment, tail padding). Returns total struct size, or None when a field
    type is unknown."""
    offset = 0
    max_align = 1
    for f in fields:
        if _is_pointer(f.ctype):
            size, kind = _POINTER_SIZE, 'ptr'
        else:
            info = _scalar_info(f.ctype)
            if info is None:
                return None
            size, kind = info
        align = min(size, 8)
        if f.count is not None:
            size *= f.count
            if kind != 'ptr':
                kind = 'bytes' if f.ctype == 'char' else kind
        offset = (offset + align - 1) // align * align
        f.offset, f.size, f.kind = offset, size, kind
        offset += size
        max_align = max(max_align, align)
    return (offset + max_align - 1) // max_align * max_align


#: string literals arrive blanked by the comment/string stripper, so the
#: ``"C"`` may appear as ``" "`` — match any (stripped) literal after extern
_EXTERN_C_RE = re.compile(r'extern\s+"[^"\n]*"\s*\{')

_FUNC_RE = re.compile(
    r'(?P<ret>[\w:<>]+(?:\s+[\w:<>]+)*(?:\s*\*+)?)\s+'
    r'(?P<name>\w+)\s*\((?P<params>[^)]*)\)\s*\{', re.S)


def parse_extern_c_functions(text):
    """``{name: CFunc}`` for every function defined inside an
    ``extern "C" { ... }`` block."""
    funcs = {}
    for m in _EXTERN_C_RE.finditer(text):
        open_idx = text.index('{', m.end() - 1)
        end = _match_brace(text, open_idx)
        if end is None:
            continue
        block = text[open_idx + 1:end]
        base_line = text.count('\n', 0, open_idx) + 1
        for fm in _FUNC_RE.finditer(block):
            raw_ret = fm.group('ret')
            if re.search(r'\b(static|inline)\b', raw_ret):
                continue  # internal linkage / helpers: not part of the C ABI
            ret = _normalize_ctype(raw_ret)
            if ret.split(' ')[0] in ('if', 'for', 'while', 'switch', 'return',
                                     'else', 'do') \
                    or fm.group('name') in ('if', 'for', 'while', 'switch'):
                continue
            params = []
            raw = ' '.join(fm.group('params').split())
            if raw and raw != 'void':
                ok = True
                for p in raw.split(','):
                    p = p.strip()
                    pm = re.match(r'^(?P<type>.+?)\s*(?P<name>\w+)$', p)
                    if not pm or not re.search(r'[\w>*]\s*$', pm.group('type')):
                        ok = False
                        break
                    params.append((_normalize_ctype(pm.group('type')),
                                   pm.group('name')))
                if not ok:
                    continue
            lineno = base_line + block.count('\n', 0, fm.start())
            funcs[fm.group('name')] = CFunc(fm.group('name'), ret, params, lineno)
    return funcs


def parse_abi_version(text):
    m = _ABI_VERSION_RE.search(text)
    return int(m.group(1)) if m else None


def _match_brace(text, open_idx):
    depth = 0
    for i in range(open_idx, len(text)):
        if text[i] == '{':
            depth += 1
        elif text[i] == '}':
            depth -= 1
            if depth == 0:
                return i
    return None


# -- ctypes-side model ------------------------------------------------------

#: ctypes scalar name -> (size, kind)
_CTYPES_SCALARS = {
    'c_bool': (1, 'uint'), 'c_char': (1, 'bytes'), 'c_byte': (1, 'int'),
    'c_ubyte': (1, 'uint'), 'c_int8': (1, 'int'), 'c_uint8': (1, 'uint'),
    'c_short': (2, 'int'), 'c_ushort': (2, 'uint'),
    'c_int16': (2, 'int'), 'c_uint16': (2, 'uint'),
    'c_int': (4, 'int'), 'c_uint': (4, 'uint'),
    'c_int32': (4, 'int'), 'c_uint32': (4, 'uint'), 'c_float': (4, 'float'),
    'c_long': (8, 'int'), 'c_ulong': (8, 'uint'),
    'c_longlong': (8, 'int'), 'c_ulonglong': (8, 'uint'),
    'c_int64': (8, 'int'), 'c_uint64': (8, 'uint'),
    'c_size_t': (8, 'uint'), 'c_ssize_t': (8, 'int'), 'c_double': (8, 'float'),
}

_CTYPES_POINTERS = {'c_void_p', 'c_char_p', 'c_wchar_p'}


class PyCType(object):
    """One resolved ctypes type expression."""

    __slots__ = ('size', 'kind', 'pointee')

    def __init__(self, size, kind, pointee=None):
        self.size = size
        self.kind = kind        # ptr / int / uint / float / bytes / unknown
        self.pointee = pointee  # class name inside POINTER(...), or None


def resolve_ctype(node):
    """:class:`PyCType` for a ctypes type AST expression, or None for shapes
    this model does not understand (those are simply not checked)."""
    chain = attr_chain(node)
    if chain is not None:
        leaf = chain.rsplit('.', 1)[-1]
        if leaf in _CTYPES_POINTERS:
            return PyCType(_POINTER_SIZE, 'ptr')
        if leaf in _CTYPES_SCALARS:
            size, kind = _CTYPES_SCALARS[leaf]
            return PyCType(size, kind)
        return None
    if isinstance(node, ast.Call):
        fchain = attr_chain(node.func) or ''
        if fchain.rsplit('.', 1)[-1] == 'POINTER' and node.args:
            inner = attr_chain(node.args[0])
            pointee = inner.rsplit('.', 1)[-1] if inner else None
            return PyCType(_POINTER_SIZE, 'ptr', pointee)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        elem = resolve_ctype(node.left)
        if elem is not None and isinstance(node.right, ast.Constant) \
                and isinstance(node.right.value, int):
            return PyCType(elem.size * node.right.value,
                           'bytes' if elem.kind == 'bytes' else elem.kind)
        return None
    if isinstance(node, ast.Constant) and node.value is None:
        return PyCType(0, 'void')
    return None


def _scalar_compatible(c_type, py):
    """A C scalar and a resolved ctypes scalar agree on size and signedness
    class (int/uint/float); ``char`` accepts either c_char or the 1-byte ints."""
    info = _scalar_info(c_type)
    if info is None:
        return True  # unknown C scalar: do not guess
    size, kind = info
    if py.size != size:
        return False
    if kind == 'bytes':
        return py.kind in ('bytes', 'int', 'uint')
    return py.kind == kind


# -- Python-side extraction -------------------------------------------------

def _iter_mirror_classes(tree):
    """(classdef, struct_name, fields) for every ctypes.Structure subclass
    with a ``mirror of ``struct X``` docstring; fields = [(name, type AST)]."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        doc = ast.get_docstring(node) or ''
        m = _MIRROR_RE.search(doc)
        if not m:
            continue
        fields = []
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            if '_fields_' not in targets:
                continue
            if isinstance(stmt.value, (ast.List, ast.Tuple)):
                for elt in stmt.value.elts:
                    if isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) >= 2 \
                            and isinstance(elt.elts[0], ast.Constant):
                        fields.append((elt.elts[0].value, elt.elts[1], elt.lineno))
        yield node, m.group(1), fields


def _iter_signature_decls(tree):
    """(func_name, 'argtypes'|'restype', value AST, lineno) for every
    ``<lib>.<func>.argtypes/restype = ...`` assignment."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Attribute) \
                or target.attr not in ('argtypes', 'restype'):
            continue
        if not isinstance(target.value, ast.Attribute):
            continue
        yield target.value.attr, target.attr, node.value, node.lineno


def _find_expected_abi(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == 'EXPECTED_ABI'
                        for t in node.targets) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            return node.value.value, node.lineno
    return None, None


# -- the checker ------------------------------------------------------------

#: parsed-cpp cache: path -> (mtime, structs, funcs, abi_version)
_cpp_cache = {}


def _parsed_cpp(path):
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}, {}, None
    cached = _cpp_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1], cached[2], cached[3]
    try:
        with open(path, 'rb') as f:
            text = f.read().decode('utf-8', 'replace')
    except OSError:
        return {}, {}, None
    text = _strip_cpp_comments_and_strings(text)
    parsed = (parse_cpp_structs(text), parse_extern_c_functions(text),
              parse_abi_version(text))
    _cpp_cache[path] = (mtime,) + parsed
    return parsed


def _sibling_cpp_model(src):
    """Merged struct/function/abi model of every ``*.cpp`` next to ``src``
    on disk (the native package dir; fixture dirs in tests). ``(None, None,
    None)`` when there are no C++ sources to check against."""
    dirname = os.path.dirname(os.path.abspath(src.path))
    if not os.path.isdir(dirname):
        return None, None, None
    paths = sorted(glob.glob(os.path.join(dirname, '*.cpp'))
                   + glob.glob(os.path.join(dirname, '*.cc')))
    if not paths:
        return None, None, None
    structs, funcs, abi = {}, {}, None
    for p in paths:
        s, f, a = _parsed_cpp(p)
        structs.update(s)
        funcs.update(f)
        if a is not None:
            abi = a
    return structs, funcs, abi


#: integer parameter names that read as a traveling bound (PT902)
_BOUND_TOKENS = frozenset({'n', 'len', 'cap', 'caps', 'size', 'count', 'pages',
                           'bytes', 'rows', 'capacity', 'width', 'height',
                           'sw', 'sh', 'dw', 'dh', 'w', 'h', 'c'})


def _is_bound_param(name, ctype):
    info = _scalar_info(ctype)
    if info is None or info[1] not in ('int', 'uint'):
        return False
    lowered = name.lower()
    if lowered.startswith(('max', 'n_', 'num')):
        return True
    return any(tok in _BOUND_TOKENS for tok in lowered.split('_'))


def _is_exempt_pointer(ctype, name):
    """NUL-terminated strings and opaque handles carry their own contract."""
    if ctype == 'char *':
        return True
    lowered = name.lower()
    return ctype == 'void *' and (lowered == 'h' or lowered.endswith('handle'))


class AbiConformanceChecker(Checker):
    code = 'PT900'
    codes = ('PT900', 'PT901', 'PT902')
    name = 'abi-conformance'
    description = ('C++ struct layouts vs ctypes mirrors (PT900), extern "C" '
                   'signatures vs argtypes/restype (PT901), pointer params '
                   'without a traveling capacity bound (PT902)')
    scope = ('*native/*.py', '*native/*.cpp', '*native/*.cc')

    def check(self, src):
        if src.is_python:
            yield from self._check_python_side(src)
        else:
            yield from self._check_pointer_bounds(src)

    # -- PT900 / PT901 (Python files, against the sibling C++ sources) ------

    def _check_python_side(self, src):
        structs, funcs, abi = _sibling_cpp_model(src)
        if structs is None:
            return  # no C++ sources next to this file: nothing to conform to
        mirrors = {}  # python class name -> C struct name
        for classdef, struct_name, py_fields in _iter_mirror_classes(src.tree):
            mirrors[classdef.name] = struct_name
            yield from self._check_struct_mirror(src, classdef, struct_name,
                                                 py_fields, structs)
        yield from self._check_signatures(src, funcs, structs, mirrors)
        yield from self._check_abi_literal(src, abi)

    def _check_struct_mirror(self, src, classdef, struct_name, py_fields, structs):
        c_fields = structs.get(struct_name)
        if c_fields is None:
            yield self.finding(
                src, classdef.lineno,
                '{} declares itself a mirror of struct {}, but no such struct '
                'exists in the native sources'.format(classdef.name, struct_name))
            return
        if layout_struct(c_fields) is None:
            yield self.finding(
                src, classdef.lineno,
                'struct {} has a field type this checker cannot lay out — '
                'extend analysis/abi.py so the {} mirror stays '
                'checkable'.format(struct_name, classdef.name))
            return
        # resolve the ctypes side with the same layout rules ctypes applies
        resolved = []
        for name, type_node, lineno in py_fields:
            py = resolve_ctype(type_node)
            if py is None:
                yield self.finding(
                    src, lineno,
                    '{}.{}: ctypes field type not understood by the ABI '
                    'checker — use a plain ctypes scalar/pointer/array '
                    'expression'.format(classdef.name, name))
                return
            resolved.append((name, py, lineno))
        offset = 0
        py_layout = []
        for name, py, lineno in resolved:
            align = min(py.size, 8) or 1
            offset = (offset + align - 1) // align * align
            py_layout.append((name, offset, py, lineno))
            offset += py.size
        if len(py_layout) != len(c_fields):
            yield self.finding(
                src, classdef.lineno,
                '{} has {} fields but struct {} has {} — the mirror drifted '
                '(every native write lands at C offsets, not Python '
                'ones)'.format(classdef.name, len(py_layout), struct_name,
                               len(c_fields)))
            return
        for (py_name, py_off, py, lineno), cf in zip(py_layout, c_fields):
            if py_name != cf.name:
                yield self.finding(
                    src, lineno,
                    '{}.{} mirrors struct {} field {!r} at this position — '
                    'field order/name drifted'.format(
                        classdef.name, py_name, struct_name, cf.name))
                continue
            if py_off != cf.offset or py.size != cf.size:
                yield self.finding(
                    src, lineno,
                    '{}.{}: offset/size ({}, {}) != struct {}.{} ({}, {}) — '
                    'layout drift means the kernel reads/writes the wrong '
                    'bytes'.format(classdef.name, py_name, py_off, py.size,
                                   struct_name, cf.name, cf.offset, cf.size))
                continue
            if (cf.kind == 'ptr') != (py.kind == 'ptr'):
                yield self.finding(
                    src, lineno,
                    '{}.{}: pointer/scalar kind mismatch with struct {}.{}'
                    .format(classdef.name, py_name, struct_name, cf.name))
            elif cf.kind in ('int', 'uint') and py.kind in ('int', 'uint') \
                    and cf.kind != py.kind:
                yield self.finding(
                    src, lineno,
                    '{}.{}: signedness mismatch with struct {}.{} ({} vs {})'
                    .format(classdef.name, py_name, struct_name, cf.name,
                            py.kind, cf.kind))

    def _check_signatures(self, src, funcs, structs, mirrors):
        mirror_by_struct = {v: k for k, v in mirrors.items()}
        for func_name, which, value, lineno in _iter_signature_decls(src.tree):
            cfunc = funcs.get(func_name)
            if cfunc is None:
                yield self.finding(
                    src, lineno,
                    '{} declares a ctypes signature for {}(), which no '
                    'extern "C" block in the native sources defines — '
                    'renamed or removed on the C side?'.format(
                        os.path.basename(src.relpath), func_name),
                    code='PT901')
                continue
            if which == 'argtypes':
                yield from self._check_argtypes(src, cfunc, value, lineno,
                                                mirror_by_struct)
            else:
                yield from self._check_restype(src, cfunc, value, lineno)
        # non-int returns MUST declare a restype: ctypes' default c_int
        # silently truncates a 64-bit return (or a pointer) to 32 bits
        decls = list(_iter_signature_decls(src.tree))
        declared = {(f, w) for f, w, _v, _l in decls}
        for func_name, which in sorted(declared):
            if which != 'argtypes' or (func_name, 'restype') in declared:
                continue
            cfunc = funcs.get(func_name)
            if cfunc is None:
                continue
            info = _scalar_info(cfunc.ret)
            needs_restype = _is_pointer(cfunc.ret) or (
                cfunc.ret != 'void' and (info is None or info[0] != 4))
            if needs_restype:
                lineno = min(l for f, _w, _v, l in decls if f == func_name)
                yield self.finding(
                    src, lineno,
                    '{}() returns {} but no restype is declared — ctypes '
                    'defaults to c_int and truncates the value to 32 '
                    'bits'.format(func_name, cfunc.ret),
                    code='PT901')

    def _check_argtypes(self, src, cfunc, value, lineno, mirror_by_struct):
        if not isinstance(value, (ast.List, ast.Tuple)):
            return
        declared = [resolve_ctype(elt) for elt in value.elts]
        if len(declared) != len(cfunc.params):
            yield self.finding(
                src, lineno,
                '{}() takes {} parameter{} but argtypes declares {} — '
                'signature drift'.format(
                    cfunc.name, len(cfunc.params),
                    '' if len(cfunc.params) == 1 else 's', len(declared)),
                code='PT901')
            return
        for i, (py, (c_type, c_name)) in enumerate(zip(declared, cfunc.params)):
            if py is None:
                continue  # unmodeled ctypes expression: not checked
            if _is_pointer(c_type):
                if py.kind != 'ptr':
                    yield self.finding(
                        src, lineno,
                        '{}() arg {} ({}: {}) is a pointer but argtypes[{}] '
                        'is a {}-byte scalar'.format(
                            cfunc.name, i, c_name, c_type, i, py.size),
                        code='PT901')
                    continue
                pointee = c_type[:-1].strip()
                expected = mirror_by_struct.get(pointee.rstrip(' *'))
                if expected and py.pointee and py.pointee != expected:
                    yield self.finding(
                        src, lineno,
                        '{}() arg {} points at struct {} but argtypes[{}] is '
                        'POINTER({}) — wrong mirror'.format(
                            cfunc.name, i, pointee, i, py.pointee),
                        code='PT901')
            elif not _scalar_compatible(c_type, py):
                yield self.finding(
                    src, lineno,
                    '{}() arg {} ({}: {}) does not match argtypes[{}] '
                    '(size/signedness drift truncates or sign-extends the '
                    'value at the boundary)'.format(
                        cfunc.name, i, c_name, c_type, i),
                    code='PT901')

    def _check_restype(self, src, cfunc, value, lineno):
        py = resolve_ctype(value)
        if py is None:
            return
        if cfunc.ret == 'void':
            if py.kind != 'void':
                yield self.finding(
                    src, lineno,
                    '{}() returns void but restype declares a value'.format(
                        cfunc.name),
                    code='PT901')
            return
        if _is_pointer(cfunc.ret):
            if py.kind != 'ptr':
                yield self.finding(
                    src, lineno,
                    '{}() returns {} but restype is not a pointer type — the '
                    'address gets truncated to 32 bits'.format(
                        cfunc.name, cfunc.ret),
                    code='PT901')
            return
        if not _scalar_compatible(cfunc.ret, py):
            yield self.finding(
                src, lineno,
                '{}() returns {} but restype disagrees on size/signedness'
                .format(cfunc.name, cfunc.ret),
                code='PT901')

    def _check_abi_literal(self, src, abi):
        expected, lineno = _find_expected_abi(src.tree)
        if expected is None:
            return
        if abi is None:
            yield self.finding(
                src, lineno,
                'EXPECTED_ABI is declared but no pstpu_abi_version() literal '
                'was found in the native sources')
        elif abi != expected:
            yield self.finding(
                src, lineno,
                'EXPECTED_ABI = {} but pstpu_abi_version() returns {} — bump '
                'both together (the version gate is the ONLY runtime defense '
                'against a stale kernel)'.format(expected, abi))

    # -- PT902 (C++ files) --------------------------------------------------

    def _check_pointer_bounds(self, src):
        text = _strip_cpp_comments_and_strings(src.text)
        for func in parse_extern_c_functions(text).values():
            unbounded = [name for ctype, name in func.params
                         if _is_pointer(ctype) and not _is_exempt_pointer(ctype, name)]
            if not unbounded:
                continue
            if any(_is_bound_param(name, ctype) for ctype, name in func.params):
                continue
            yield self.finding(
                src, func.lineno,
                'extern "C" {}() takes pointer parameter{} {} with no '
                'capacity/length parameter traveling in the signature — the '
                'kernel can only bounds-check what the caller hands it '
                '(PT503 generalized to the whole call surface)'.format(
                    func.name, '' if len(unbounded) == 1 else 's',
                    '/'.join(unbounded)),
                code='PT902')
