"""Deterministic interleaving scheduler: the dynamic half of the thread-plane
trust story (static half: ``petastorm_tpu/analysis/races.py``, rules
PT1300-PT1303).

Loom-style model checking for the Python plane: while a :class:`Scheduler`
run is active, ``threading.Lock/RLock/Condition/Event/Thread`` are
monkeypatched so that **exactly one thread runs at a time** and every
synchronization operation is a *scheduling point* where the controller picks
which thread runs next.  The choice sequence — the *schedule* — is the
complete description of the interleaving:

* schedules are **recorded** (``RunResult.schedule`` is a comma-separated
  list of thread indices) and **replayable byte-for-byte**
  (:class:`ReplayStrategy`, or ``PSTPU_SCHEDULE=`` through the explorer);
* a seeded :class:`RandomStrategy` makes exploration reproducible;
* a **vector-clock tracker** (:meth:`Scheduler.track`) flags attribute
  write/write and write/read pairs on designated objects with no
  happens-before edge — a genuine data-race detector, not a failure-biased
  stress test;
* **deadlocks** are detected exactly (no runnable thread, unfinished
  threads remain) and reported with each thread's blocked resource.

Timed waits (``Condition.wait(timeout=...)``, ``Event.wait(timeout)``,
``lock.acquire(timeout=...)``, ``Thread.join(timeout)``) are modeled as
*timed-runnable*: the thread may be scheduled while its resource is still
unavailable, and doing so means **the timeout fired**.  No real clock is
consulted, so every run is deterministic and timeout paths are explorable
like any other interleaving.

Happens-before edges tracked by the vector clocks:

* lock release -> (next) acquire of the same lock
* ``Condition.notify`` -> the woken waiter
* ``Event.set`` -> a successful ``Event.wait``
* ``Thread.start`` -> the child's first step
* thread exit -> a successful ``Thread.join``

Scope and caveats (docs/analysis.md "reading a schedule trace"):

* Only threads created *during the run* (through the patched
  ``threading.Thread``) are scheduled.  Scenario code must create its
  components inside the run so their primitives are the scheduled kind.
* Scheduled primitives degrade gracefully after the run: a ``SchedLock``
  that leaks into post-run code falls back to a real lock, so e.g. metrics
  counters created mid-run keep working.
* Real (unpatched) locks taken by library code are invisible; that is safe
  as long as no code holds one across a scheduling point — true for this
  repo's import-time singletons (metrics/trace registries), whose critical
  sections contain no patched operations.
"""

from __future__ import annotations

import os
import random
import sys
import threading as _threading
import traceback

#: captured originals — the scheduler's own machinery must keep working
#: while the ``threading`` module attributes are patched
_real_Lock = _threading.Lock
_real_RLock = _threading.RLock
_real_Condition = _threading.Condition
_real_Event = _threading.Event
_real_Thread = _threading.Thread
_real_current_thread = _threading.current_thread
_real_get_ident = _threading.get_ident
_real_Semaphore = _threading.Semaphore

#: one run at a time per process (the patches are process-global)
_RUN_MUTEX = _real_Lock()

#: the active scheduler (None outside a run)
_CURRENT = None


def current_scheduler():
    """The active :class:`Scheduler`, or None outside a run."""
    return _CURRENT


class SchedulerError(Exception):
    """Misuse of the scheduler (not a finding about the component)."""


class ScheduleDivergence(SchedulerError):
    """A replayed schedule named a thread that is not runnable at that
    step — the code under test changed since the schedule was recorded."""


class _AbortRun(BaseException):
    """Unwinds scheduled threads when a run is torn down.  BaseException so
    component-level ``except Exception`` blocks cannot swallow it."""


class Race(object):
    """One detected data race (a pair of conflicting accesses with no
    happens-before edge)."""

    __slots__ = ('kind', 'obj', 'attr', 'first', 'second', 'step')

    def __init__(self, kind, obj, attr, first, second, step):
        self.kind = kind          # 'write/write' or 'write/read'
        self.obj = obj            # tracked object label
        self.attr = attr
        self.first = first        # thread name of the earlier access
        self.second = second      # thread name of the later access
        self.step = step

    def key(self):
        return (self.kind, self.obj, self.attr)

    def describe(self):
        return ('{} race on {}.{}: {!r} and {!r} accessed it with no '
                'happens-before edge (detected at step {})'.format(
                    self.kind, self.obj, self.attr, self.first, self.second,
                    self.step))

    def __repr__(self):
        return 'Race({})'.format(self.describe())


class RunResult(object):
    """Outcome of one scheduled run."""

    __slots__ = ('schedule', 'steps', 'races', 'deadlock', 'errors',
                 'steps_exhausted', 'divergence', 'stalled')

    def __init__(self, schedule, steps, races, deadlock, errors,
                 steps_exhausted, divergence, stalled):
        self.schedule = schedule          # 'i,j,k,...' — the replay string
        self.steps = steps
        self.races = races                # [Race]
        self.deadlock = deadlock          # None or a description string
        self.errors = errors              # [(thread_name, repr, traceback)]
        self.steps_exhausted = steps_exhausted
        self.divergence = divergence
        self.stalled = stalled            # a thread ran without yielding

    @property
    def ok(self):
        return (not self.races and self.deadlock is None and not self.errors
                and not self.inconclusive)

    @property
    def inconclusive(self):
        """The run neither passed nor failed the component: the budget ran
        out or the schedule no longer applies."""
        return self.steps_exhausted or self.divergence or self.stalled

    def describe(self):
        lines = []
        for r in self.races:
            lines.append(r.describe())
        if self.deadlock:
            lines.append('deadlock: {}'.format(self.deadlock))
        for name, err, _tb in self.errors:
            lines.append('thread {!r} raised: {}'.format(name, err))
        if self.steps_exhausted:
            lines.append('inconclusive: step budget exhausted ({} steps)'
                         .format(self.steps))
        if self.divergence:
            lines.append('inconclusive: replayed schedule diverged')
        if self.stalled:
            lines.append('inconclusive: a thread ran without reaching a '
                         'scheduling point (un-instrumented spin loop?)')
        if not lines:
            lines.append('ok')
        lines.append('schedule: {}'.format(self.schedule))
        return '\n'.join(lines)


# -- scheduling strategies ----------------------------------------------------

def _default_pick(runnable, prev):
    """The non-preempting default: keep running the previous thread when it
    can make real progress; otherwise the lowest-index thread that can.
    Threads whose only move is firing a wait timeout come last, so the
    default schedule never spins a polling loop while others could run."""
    progress = [t for t in runnable
                if t.status != 'timed' or t.resource is None
                or t.resource.ready(t)]
    pool = progress or runnable
    for t in pool:
        if t.index == prev:
            return t
    return min(pool, key=lambda t: t.index)


class RandomStrategy(object):
    """Uniformly random choice among runnable threads, from a seeded RNG —
    the exploration workhorse.  Same seed + same component = same schedule."""

    def __init__(self, seed):
        self.seed = seed
        self._rng = random.Random(seed)

    def choose(self, runnable, prev):
        return runnable[self._rng.randrange(len(runnable))]


class ReplayStrategy(object):
    """Byte-for-byte replay of a recorded schedule; raises
    :class:`ScheduleDivergence` if a recorded choice is not runnable.  Past
    the end of the recording, falls back to the deterministic default."""

    def __init__(self, schedule):
        self._schedule = list(schedule)
        self._i = 0

    def choose(self, runnable, prev):
        if self._i < len(self._schedule):
            want = self._schedule[self._i]
            self._i += 1
            for t in runnable:
                if t.index == want:
                    return t
            raise ScheduleDivergence(
                'schedule step {} wants thread {} but runnable set is {}'
                .format(self._i - 1, want,
                        sorted(t.index for t in runnable)))
        return _default_pick(runnable, prev)


class PrefixStrategy(object):
    """Forced choice prefix, then the non-preempting default — the unit of
    bounded-preemption DFS (each DFS node is a prefix)."""

    def __init__(self, prefix):
        self._prefix = tuple(prefix)
        self._i = 0

    def choose(self, runnable, prev):
        if self._i < len(self._prefix):
            want = self._prefix[self._i]
            self._i += 1
            for t in runnable:
                if t.index == want:
                    return t
            raise ScheduleDivergence(
                'DFS prefix step {} wants thread {} but runnable set is {}'
                .format(self._i - 1, want,
                        sorted(t.index for t in runnable)))
        return _default_pick(runnable, prev)


def parse_schedule(text):
    """Parse a ``'0,1,1,0'`` schedule string (the :data:`PSTPU_SCHEDULE`
    format) into a list of thread indices."""
    try:
        return [int(tok) for tok in text.split(',') if tok.strip() != '']
    except ValueError:
        raise SchedulerError('malformed schedule string: {!r}'.format(text))


# -- thread state -------------------------------------------------------------

class _TState(object):
    """Controller-side state of one scheduled thread."""

    __slots__ = ('index', 'name', 'gate', 'status', 'resource', 'clock',
                 'final_clock', 'handle', 'aborting', 'in_access')

    def __init__(self, index, name, handle):
        self.index = index
        self.name = name
        self.gate = _real_Semaphore(0)
        self.status = 'runnable'   # runnable | blocked | timed | finished
        self.resource = None       # what a blocked/timed thread waits for
        self.clock = {index: 1}    # vector clock
        self.final_clock = None
        self.handle = handle       # the SchedThread facade
        self.aborting = False
        self.in_access = False     # re-entrancy guard for attr tracking

    def tick(self):
        self.clock[self.index] = self.clock.get(self.index, 0) + 1

    def join_clock(self, other):
        for k, v in other.items():
            if v > self.clock.get(k, 0):
                self.clock[k] = v

    def ordered_before(self, owner_index, epoch):
        """True when an access by thread ``owner_index`` at ``epoch``
        happens-before this thread's current point."""
        return epoch <= self.clock.get(owner_index, 0)


def _export_clock(state):
    """Snapshot ``state``'s clock for a sync object and advance the epoch
    (the standard release protocol)."""
    snap = dict(state.clock)
    state.tick()
    return snap


def _join_into(target, clock):
    for k, v in clock.items():
        if v > target.get(k, 0):
            target[k] = v


# -- scheduled primitives -----------------------------------------------------

class _SchedLockBase(object):
    """Shared machinery of the scheduled Lock/RLock.  Outside an active run
    (or from an unmanaged thread) every operation degrades to a private real
    lock, so primitives created mid-run stay usable afterwards."""

    _REENTRANT = False

    def __init__(self, sched, name=None):
        self._sched = sched
        self._name = name or '{}#{}'.format(type(self).__name__,
                                            sched._next_serial())
        self._owner = None
        self._count = 0
        self._clock = {}
        self._fallback = _real_RLock() if self._REENTRANT else _real_Lock()

    def _state(self):
        sched = self._sched
        if sched is None or not sched._active or sched is not _CURRENT:
            return None
        return sched._state_for_current()

    def ready(self, state):
        return self._owner is None or (self._REENTRANT
                                       and self._owner is state)

    def acquire(self, blocking=True, timeout=-1):
        st = self._state()
        if st is None:
            if timeout is not None and timeout > 0:
                return self._fallback.acquire(blocking, timeout)
            return self._fallback.acquire(blocking)
        sched = self._sched
        if st.aborting:
            self._owner, self._count = st, 1
            return True
        sched._yield(st)  # the decision point *before* the attempt
        while True:
            if self._owner is None:
                self._owner, self._count = st, 1
                st.join_clock(self._clock)
                return True
            if self._REENTRANT and self._owner is st:
                self._count += 1
                return True
            if not blocking:
                return False
            timed = timeout is not None and timeout > 0
            sched._block(st, self, timed)
            if st.aborting:
                self._owner, self._count = st, 1
                return True
            if not self.ready(st):
                if timed:
                    return False  # scheduled while unavailable = timeout fired
                continue

    def release(self):
        st = self._state()
        if st is None:
            return self._fallback.release()
        if self._owner is not st:
            raise RuntimeError('release of un-acquired {}'.format(self._name))
        self._count -= 1
        if self._count > 0:
            return
        _join_into(self._clock, st.clock)
        st.tick()
        self._owner = None
        if not st.aborting:
            self._sched._yield(st)  # let a waiter grab it right here

    def locked(self):
        if self._state() is None:
            # approximation for the fallback path (matches Lock.locked())
            if self._fallback.acquire(False):
                self._fallback.release()
                return False
            return True
        return self._owner is not None

    # Condition plumbing (mirrors CPython's _release_save/_acquire_restore)
    def _release_save(self):
        st = self._sched._state_for_current()
        count = self._count
        _join_into(self._clock, st.clock)
        st.tick()
        self._owner = None
        self._count = 0
        return count

    def _acquire_restore(self, count):
        self.acquire()
        self._count = count

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.release()
        return False

    def __repr__(self):
        return '<{} owner={}>'.format(
            self._name, self._owner.name if self._owner else None)


class SchedLock(_SchedLockBase):
    _REENTRANT = False


class SchedRLock(_SchedLockBase):
    _REENTRANT = True


class _CondWaiter(object):
    """One parked ``Condition.wait`` — the blocked thread's resource."""

    __slots__ = ('state', 'notified', 'wake_clock')

    def __init__(self, state):
        self.state = state
        self.notified = False
        self.wake_clock = None

    def ready(self, state):
        return self.notified


class SchedCondition(object):
    """Scheduled ``threading.Condition``.  Waits park the thread (releasing
    the lock fully, RLock count preserved); ``notify`` hands the notifier's
    clock to the woken waiter, and timed waits may fire their timeout
    whenever the scheduler picks the waiter while it is un-notified."""

    def __init__(self, sched, lock=None):
        self._sched = sched
        self._lock = lock if lock is not None else SchedRLock(sched)
        self._waiters = []
        self.acquire = self._lock.acquire
        self.release = self._lock.release

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self._lock.release()
        return False

    def _owned_state(self):
        st = self._sched._state_for_current() if self._sched._active else None
        if st is None:
            raise SchedulerError(
                'Condition used from an unmanaged thread during a run')
        if self._lock._owner is not st:
            raise RuntimeError('cannot wait on un-acquired lock')
        return st

    def wait(self, timeout=None):
        st = self._owned_state()
        if st.aborting:
            return False
        waiter = _CondWaiter(st)
        self._waiters.append(waiter)
        saved = self._lock._release_save()
        self._sched._block(st, waiter, timed=timeout is not None)
        if not waiter.notified:
            try:
                self._waiters.remove(waiter)  # the timeout fired
            except ValueError:
                pass
        self._lock._acquire_restore(saved)
        if waiter.wake_clock is not None:
            st.join_clock(waiter.wake_clock)
        return waiter.notified

    def wait_for(self, predicate, timeout=None):
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n=1):
        st = self._owned_state()
        woken = 0
        snap = None
        while self._waiters and woken < n:
            waiter = self._waiters.pop(0)  # FIFO — deterministic wake order
            waiter.notified = True
            if snap is None:
                snap = dict(st.clock)
            waiter.wake_clock = snap
            woken += 1
        if woken:
            st.tick()

    def notify_all(self):
        self.notify(len(self._waiters))

    notifyAll = notify_all


class _EventWait(object):
    __slots__ = ('event',)

    def __init__(self, event):
        self.event = event

    def ready(self, state):
        return self.event._flag


class SchedEvent(object):
    """Scheduled ``threading.Event``.  ``set -> successful wait`` is a
    happens-before edge; a timed wait scheduled while unset = timeout."""

    def __init__(self, sched):
        self._sched = sched
        self._flag = False
        self._clock = {}
        self._name = 'Event#{}'.format(sched._next_serial())

    def is_set(self):
        return self._flag

    isSet = is_set

    def set(self):
        sched = self._sched
        st = sched._state_for_current() if sched._active else None
        if st is None:
            self._flag = True
            return
        _join_into(self._clock, st.clock)
        st.tick()
        self._flag = True
        if not st.aborting:
            sched._yield(st)

    def clear(self):
        self._flag = False

    def wait(self, timeout=None):
        sched = self._sched
        st = sched._state_for_current() if sched._active else None
        if st is None:
            raise SchedulerError(
                'Event.wait from an unmanaged thread during a run')
        if st.aborting:
            return self._flag
        sched._yield(st)
        if self._flag:
            st.join_clock(self._clock)
            return True
        sched._block(st, _EventWait(self), timed=timeout is not None)
        if self._flag:
            st.join_clock(self._clock)
            return True
        return False  # the timeout fired


class _JoinWait(object):
    __slots__ = ('target',)

    def __init__(self, target):
        self.target = target

    def ready(self, state):
        return self.target.status == 'finished'


class SchedThread(object):
    """Scheduled stand-in for ``threading.Thread`` (the composition API:
    ``Thread(target=...)``; subclassing is not supported — none of the
    scheduled components subclass Thread)."""

    def __init__(self, group=None, target=None, name=None, args=(),
                 kwargs=None, daemon=None):
        sched = _CURRENT
        if sched is None or not sched._active:
            raise SchedulerError('SchedThread created outside an active run')
        self._sched = sched
        self._target = target
        self._args = tuple(args)
        self._kwargs = dict(kwargs or {})
        self.name = name or 'Thread-{}'.format(sched._next_serial())
        self.daemon = True if daemon is None else daemon
        self._state = None

    def start(self):
        if self._state is not None:
            raise RuntimeError('threads can only be started once')
        self._sched._spawn(self)

    def run(self):
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def join(self, timeout=None):
        self._sched._join_thread(self, timeout)

    def is_alive(self):
        return self._state is not None and self._state.status != 'finished'

    @property
    def ident(self):
        return None if self._state is None else self._state.index

    def __repr__(self):
        return '<SchedThread {} idx={}>'.format(
            self.name, None if self._state is None else self._state.index)


# -- tracked-attribute bookkeeping --------------------------------------------

_SYNC_TYPES_CACHE = None


def _sync_types():
    global _SYNC_TYPES_CACHE
    if _SYNC_TYPES_CACHE is None:
        _SYNC_TYPES_CACHE = (
            SchedLock, SchedRLock, SchedCondition, SchedEvent, SchedThread,
            type(_real_Lock()), type(_real_RLock()), _real_Condition,
            type(_real_Event()), _real_Thread,
        )
    return _SYNC_TYPES_CACHE


class _TrackInfo(object):
    __slots__ = ('label', 'names', 'obj')

    def __init__(self, label, names, obj):
        self.label = label
        self.names = names
        self.obj = obj   # strong ref: keeps id(obj) stable for the run


def _data_attr_names(obj):
    """The instance's *data* attribute names at track time: ``__dict__``
    keys plus every ``__slots__`` entry in the MRO, minus sync primitives,
    callables and dunders.  Components define all state in ``__init__``, so
    the snapshot is complete by the time a scenario calls ``track()``."""
    names = set()
    d = getattr(type(obj), '__dict__', {})
    inst = object.__getattribute__(obj, '__dict__') if hasattr(obj, '__dict__') else {}
    names.update(inst.keys())
    for klass in type(obj).__mro__:
        names.update(getattr(klass, '__slots__', ()) or ())
    keep = set()
    for name in names:
        if name.startswith('__'):
            continue
        try:
            value = object.__getattribute__(obj, name)
        except AttributeError:
            continue
        if isinstance(value, _sync_types()) or callable(value):
            continue
        keep.add(name)
    return keep


# -- the scheduler ------------------------------------------------------------

class Scheduler(object):
    """One deterministic run: patches ``threading``, runs the scenario as
    thread 0, and schedules every spawned thread one step at a time.

    :param strategy: a choice strategy (:class:`RandomStrategy`,
        :class:`ReplayStrategy`, :class:`PrefixStrategy`); default is the
        deterministic non-preempting policy.
    :param max_steps: hard cap on scheduling decisions (livelock backstop);
        exceeding it makes the run *inconclusive*, not failed.
    :param step_timeout: real-time watchdog per step — fires only when a
        scheduled thread runs without ever reaching a scheduling point
        (an un-instrumented spin loop), which is a scenario bug.
    """

    def __init__(self, strategy=None, max_steps=20000, step_timeout=30.0):
        self._strategy = strategy
        self._threads = []
        self._ctl = _real_Semaphore(0)
        self._by_ident = {}
        self._trace = []
        self._decisions = []   # (runnable index tuple, chosen, prev)
        self.races = []
        self._race_keys = set()
        self.errors = []
        self.deadlock = None
        self.steps = 0
        self.max_steps = max_steps
        self.step_timeout = step_timeout
        self._steps_exhausted = False
        self._divergence = False
        self._stalled = False
        self._abort = False
        self._active = False
        self._serial = 0
        self._last_chosen = None
        self._tracked = {}
        self._access = {}          # (id(obj), attr) -> {'w': ..., 'r': {...}}
        self._patched_classes = {}
        self._saved_threading = None

    # -- public helpers for scenarios ----------------------------------------

    def track(self, obj, name=None, atomic=()):
        """Register ``obj`` for vector-clock race detection.  Every data
        attribute present at track time is watched; ``atomic`` names an
        allowlist of attributes exempted by design (documented GIL-atomic
        signal flags — each exemption should cite why)."""
        cls = type(obj)
        self._instrument_class(cls)
        names = _data_attr_names(obj) - set(atomic)
        label = name or cls.__name__
        self._tracked[id(obj)] = _TrackInfo(label, frozenset(names), obj)
        return obj

    def yield_now(self):
        """Explicit scheduling point, for scenario loops with no patched
        operation of their own."""
        st = self._state_for_current()
        if st is not None and not st.aborting:
            self._yield(st)

    # -- run ------------------------------------------------------------------

    def run(self, fn):
        """Execute ``fn`` as scheduled thread 0 ('main') and schedule it plus
        everything it spawns to completion.  Returns a :class:`RunResult`."""
        global _CURRENT
        if self._active:
            raise SchedulerError('Scheduler.run is not reentrant')
        if self._strategy is None:
            self._strategy = PrefixStrategy(())
        _RUN_MUTEX.acquire()
        try:
            self._install_patches()
            _CURRENT = self
            self._active = True
            root = SchedThread(target=fn, name='main')
            self._spawn(root, parent=None)
            self._controller()
        finally:
            self._active = False
            _CURRENT = None
            self._restore_patches()
            _RUN_MUTEX.release()
        return RunResult(
            schedule=','.join(str(i) for i in self._trace),
            steps=self.steps,
            races=list(self.races),
            deadlock=self.deadlock,
            errors=list(self.errors),
            steps_exhausted=self._steps_exhausted,
            divergence=self._divergence,
            stalled=self._stalled,
        )

    @property
    def decisions(self):
        """Per-step (runnable index tuple, chosen index, previous index) —
        the bounded-preemption explorer's branching data."""
        return list(self._decisions)

    # -- controller -----------------------------------------------------------

    def _controller(self):
        while True:
            unfinished = [t for t in self._threads if t.status != 'finished']
            if not unfinished:
                return
            if self.errors:
                self._abort_run(unfinished)
                return
            runnable = [t for t in unfinished if self._runnable(t)]
            if not runnable:
                self.deadlock = '; '.join(
                    'thread {} ({!r}) blocked on {!r}'.format(
                        t.index, t.name, t.resource)
                    for t in unfinished)
                self._abort_run(unfinished)
                return
            if self.steps >= self.max_steps:
                self._steps_exhausted = True
                self._abort_run(unfinished)
                return
            try:
                chosen = self._strategy.choose(runnable, self._last_chosen)
            except ScheduleDivergence:
                self._divergence = True
                self._abort_run(unfinished)
                return
            self._decisions.append((tuple(t.index for t in runnable),
                                    chosen.index, self._last_chosen))
            self._trace.append(chosen.index)
            self._last_chosen = chosen.index
            self.steps += 1
            if not self._step(chosen):
                return

    def _runnable(self, t):
        if t.status == 'runnable':
            return True
        if t.status == 'timed':
            return True  # scheduling an unavailable timed wait = timeout
        if t.status == 'blocked':
            return t.resource is not None and t.resource.ready(t)
        return False

    def _step(self, t):
        t.gate.release()
        if not self._ctl.acquire(timeout=self.step_timeout):
            self._stalled = True
            self.errors.append((t.name,
                                'no scheduling point reached within {}s'
                                .format(self.step_timeout), ''))
            return False
        return True

    def _abort_run(self, unfinished):
        """Unwind every live thread: wake it so its next scheduling point
        raises :class:`_AbortRun`, which ``_thread_main`` absorbs."""
        self._abort = True
        for _round in range(len(self._threads) * 4 + 8):
            live = [t for t in self._threads if t.status != 'finished']
            if not live:
                return
            for t in live:
                t.gate.release()
            for t in live:
                if not self._ctl.acquire(timeout=self.step_timeout):
                    self._stalled = True
                    return  # leaked daemon thread; surfaced as inconclusive

    # -- thread plumbing ------------------------------------------------------

    def _next_serial(self):
        self._serial += 1
        return self._serial

    def _state_for_current(self):
        return self._by_ident.get(_real_get_ident())

    def _require_state(self):
        st = self._state_for_current()
        if st is None:
            raise SchedulerError(
                'scheduled primitive used from an unmanaged thread')
        return st

    def _spawn(self, handle, parent='caller'):
        if parent == 'caller':
            parent = self._require_state()
        index = len(self._threads)
        state = _TState(index, handle.name, handle)
        handle._state = state
        if parent is not None:
            state.join_clock(parent.clock)
            parent.tick()
        self._threads.append(state)
        real = _real_Thread(target=self._thread_main, args=(state, handle),
                            daemon=True,
                            name='pstpu-sched-{}'.format(handle.name))
        real.start()
        if parent is not None and not parent.aborting:
            self._yield(parent)  # thread creation is a scheduling point

    def _thread_main(self, state, handle):
        self._by_ident[_real_get_ident()] = state
        state.gate.acquire()   # wait to be scheduled the first time
        state.status = 'running'
        try:
            if self._abort:
                raise _AbortRun()
            handle.run()
        except _AbortRun:
            pass
        except BaseException as e:  # noqa: BLE001 - every scenario failure must reach the report
            if not self._abort:
                self.errors.append((state.name, repr(e),
                                    traceback.format_exc()))
        finally:
            state.final_clock = dict(state.clock)
            state.status = 'finished'
            self._ctl.release()

    def _join_thread(self, handle, timeout):
        st = self._require_state()
        if st.aborting:
            return
        target = handle._state
        if target is None:
            raise RuntimeError('cannot join thread before it is started')
        self._yield(st)
        while target.status != 'finished':
            self._block(st, _JoinWait(target), timed=timeout is not None)
            if st.aborting:
                return
            if target.status != 'finished' and timeout is not None:
                return  # the join timeout fired
        st.join_clock(target.final_clock)

    # -- scheduling points ----------------------------------------------------

    def _yield(self, state, status='runnable', resource=None):
        """Park the calling thread and hand control to the controller; the
        thread resumes when the controller next schedules it."""
        if self._abort and not state.aborting:
            state.aborting = True
            raise _AbortRun()
        if state.aborting:
            return
        state.status = status
        state.resource = resource
        self._ctl.release()
        state.gate.acquire()
        state.status = 'running'
        state.resource = None
        if self._abort and not state.aborting:
            state.aborting = True
            raise _AbortRun()

    def _block(self, state, resource, timed):
        self._yield(state, status='timed' if timed else 'blocked',
                    resource=resource)

    # -- attribute tracking ---------------------------------------------------

    def _instrument_class(self, cls):
        if cls in self._patched_classes:
            return
        orig_set = cls.__setattr__
        orig_get = cls.__getattribute__

        def tracked_setattr(obj, name, value, _orig=orig_set):
            sched = _CURRENT
            if sched is not None:
                sched._on_access(obj, name, True)
            _orig(obj, name, value)

        def tracked_getattribute(obj, name, _orig=orig_get):
            sched = _CURRENT
            if sched is not None:
                sched._on_access(obj, name, False)
            return _orig(obj, name)

        cls.__setattr__ = tracked_setattr
        cls.__getattribute__ = tracked_getattribute
        self._patched_classes[cls] = (orig_set, orig_get)

    def _on_access(self, obj, attr, is_write):
        if not self._active or self._abort:
            return
        info = self._tracked.get(id(obj))
        if info is None or attr not in info.names:
            return
        st = self._state_for_current()
        if st is None or st.aborting or st.in_access:
            return
        st.in_access = True
        try:
            if is_write:
                # a tracked write is a scheduling point: the explorer can
                # interleave other threads right before the store lands
                st.in_access = False
                self._yield(st)
                st.in_access = True
            self._race_check(info, obj, attr, st, is_write)
        finally:
            st.in_access = False

    def _race_check(self, info, obj, attr, st, is_write):
        cell = self._access.get((id(obj), attr))
        if cell is None:
            cell = self._access[(id(obj), attr)] = {'w': None, 'r': {}}
        write = cell['w']
        if write is not None:
            w_state, w_epoch = write
            if w_state is not st and not st.ordered_before(w_state.index,
                                                           w_epoch):
                kind = 'write/write' if is_write else 'write/read'
                self._report_race(kind, info.label, attr, w_state, st)
        if is_write:
            for r_state, r_epoch in cell['r'].items():
                if r_state is not st and not st.ordered_before(r_state.index,
                                                              r_epoch):
                    self._report_race('write/read', info.label, attr,
                                      r_state, st)
            cell['w'] = (st, st.clock[st.index])
            cell['r'] = {}
        else:
            cell['r'][st] = st.clock[st.index]

    def _report_race(self, kind, label, attr, first, second):
        race = Race(kind, label, attr, first.name, second.name, self.steps)
        if race.key() not in self._race_keys:
            self._race_keys.add(race.key())
            self.races.append(race)

    # -- patching -------------------------------------------------------------

    def _install_patches(self):
        self._saved_threading = {
            name: getattr(_threading, name)
            for name in ('Lock', 'RLock', 'Condition', 'Event', 'Thread',
                         'current_thread')
        }
        _threading.Lock = _lock_factory
        _threading.RLock = _rlock_factory
        _threading.Condition = _condition_factory
        _threading.Event = _event_factory
        _threading.Thread = _thread_factory
        _threading.current_thread = _current_thread

    def _restore_patches(self):
        if self._saved_threading:
            for name, value in self._saved_threading.items():
                setattr(_threading, name, value)
            self._saved_threading = None
        for cls, (orig_set, orig_get) in self._patched_classes.items():
            cls.__setattr__ = orig_set
            cls.__getattribute__ = orig_get
        self._patched_classes.clear()


# -- patched threading factories ----------------------------------------------

def _caller_is_threading():
    """True when a patched factory is being invoked from ``threading.py``
    itself.  CPython's primitives compose through module globals (a
    ``Semaphore`` builds a ``Condition``, a ``Thread`` builds ``Event``\\ s),
    so stdlib internals must always get the *real* classes — only component
    code gets the scheduled kind."""
    try:
        frame = sys._getframe(1)
    except ValueError:
        return False
    # Walk past this module's own helper/factory frames to the true caller.
    while frame is not None \
            and frame.f_globals.get('__name__') == __name__:
        frame = frame.f_back
    return frame is not None \
        and frame.f_globals.get('__name__') == 'threading'


def _in_run():
    if _caller_is_threading():
        return None
    sched = _CURRENT
    if sched is not None and sched._active \
            and sched._state_for_current() is not None:
        return sched
    return None


def _lock_factory():
    sched = _in_run()
    return SchedLock(sched) if sched is not None else _real_Lock()


def _rlock_factory():
    sched = _in_run()
    return SchedRLock(sched) if sched is not None else _real_RLock()


def _condition_factory(lock=None):
    sched = _in_run()
    if sched is not None:
        return SchedCondition(sched, lock)
    return _real_Condition(lock)


def _event_factory():
    sched = _in_run()
    return SchedEvent(sched) if sched is not None else _real_Event()


def _thread_factory(group=None, target=None, name=None, args=(), kwargs=None,
                    daemon=None):
    sched = _in_run()
    if sched is not None:
        return SchedThread(group=group, target=target, name=name, args=args,
                           kwargs=kwargs, daemon=daemon)
    return _real_Thread(group=group, target=target, name=name, args=args,
                        kwargs=kwargs, daemon=daemon)


def _current_thread():
    if _caller_is_threading():
        return _real_current_thread()
    sched = _CURRENT
    if sched is not None and sched._active:
        st = sched._state_for_current()
        if st is not None:
            return st.handle
    return _real_current_thread()


#: env var the explorer consults for byte-for-byte replay
SCHEDULE_ENV = 'PSTPU_SCHEDULE'


def schedule_from_env(environ=os.environ):
    """The ``PSTPU_SCHEDULE`` replay schedule, parsed, or None."""
    raw = environ.get(SCHEDULE_ENV)
    if not raw:
        return None
    return parse_schedule(raw)


__all__ = [
    'PrefixStrategy', 'Race', 'RandomStrategy', 'ReplayStrategy', 'RunResult',
    'SCHEDULE_ENV', 'SchedCondition', 'SchedEvent', 'SchedLock', 'SchedRLock',
    'SchedThread', 'ScheduleDivergence', 'Scheduler', 'SchedulerError',
    'current_scheduler', 'parse_schedule', 'schedule_from_env',
]
