"""Deterministic-schedule scenarios over the real thread-plane components.

Each scenario is ``fn(sched)``: it constructs its component **inside the
run** (so the component's primitives are the scheduled kind), registers the
objects whose attributes the vector-clock tracker should watch
(:meth:`Scheduler.track`), drives a real multi-threaded workload to
completion, and asserts the component's own invariants.  The explorer then
hammers the scenario with hundreds of schedules; any race, deadlock, or
broken invariant fails with a replayable schedule string.

Two registries:

* :data:`SCENARIOS` — the real components; tier-1 requires every one to
  survive exploration (the soundness direction).
* :data:`DEFECT_SCENARIOS` — seeded-defect fixtures (a torn counter, an
  ABBA deadlock, the pre-fix ventilator flag protocol); the explorer must
  *catch* each one (the teeth direction).  They are reachable from
  ``petastorm-tpu-race explore`` only by explicit name.

Scenario-design rules (docs/analysis.md "reading a schedule trace"):

* never spin on an unsynchronized flag — every wait goes through a patched
  ``Condition``/``Event`` so the scheduler sees the dependency (an
  un-instrumented spin loop trips the stall watchdog);
* handshakes use *untimed* waits (the scheduler proves they are woken);
  component-internal polls keep their timed waits, which the scheduler
  models as timeouts it may fire at will;
* keep workloads small: exploration runs hundreds of schedules in tier-1.
"""

from __future__ import annotations

import threading


# -- seeded-defect fixtures ---------------------------------------------------

class TornCounter(object):
    """Deliberate data race: ``bump_unsafe`` does a read-modify-write of
    ``value`` with no lock while ``bump_safe`` mutates it under one.  The
    vector-clock tracker must flag value's write/write pair on every
    schedule — this is the explorer's teeth test."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump_safe(self):
        with self._lock:
            self.value = self.value + 1

    def bump_unsafe(self):
        self.value = self.value + 1


class SafeCounter(object):
    """Race-free twin of :class:`TornCounter`: every access holds the lock.
    Must survive 500+ schedules without a single report (soundness)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self):
        with self._lock:
            self.value = self.value + 1

    def read(self):
        with self._lock:
            return self.value


class _PreFixFlags(object):
    """The ConcurrentVentilator flag protocol *before* this PR's fix: the
    worker loop reads ``_stop_requested``/writes ``_completed`` bare while
    ``stop()`` writes/reads them bare from another thread.  Kept as a
    fixture so the regression test proves the explorer catches exactly the
    defect class that was fixed in ``workers/ventilator.py``."""

    def __init__(self):
        self._cv = threading.Condition()
        self._stop_requested = False
        self._completed = False

    def loop(self):
        while not self._stop_requested:       # bare read — the defect
            with self._cv:
                self._cv.wait(timeout=0.1)
        self._completed = True                # bare write — the defect

    def stop(self):
        self._stop_requested = True           # bare write — the defect
        with self._cv:
            self._cv.notify_all()


def torn_counter(sched):
    counter = sched.track(TornCounter(), name='TornCounter')
    t1 = threading.Thread(target=counter.bump_safe, name='safe')
    t2 = threading.Thread(target=counter.bump_unsafe, name='unsafe')
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def safe_counter(sched):
    counter = sched.track(SafeCounter(), name='SafeCounter')
    threads = [threading.Thread(target=counter.bump, name='bump-%d' % i)
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter.read() == 2


def abba_deadlock(sched):
    """Classic lock-order inversion; some schedules deadlock (the detector
    must say so, with both threads' blocked resources)."""
    a = threading.Lock()
    b = threading.Lock()

    def one():
        with a:
            with b:
                pass

    def two():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=one, name='ab')
    t2 = threading.Thread(target=two, name='ba')
    t1.start()
    t2.start()
    t1.join()
    t2.join()


def prefix_ventilator_flags(sched):
    comp = sched.track(_PreFixFlags(), name='PreFixFlags')
    worker = threading.Thread(target=comp.loop, name='worker')
    worker.start()
    comp.stop()
    worker.join()


# -- real-component scenarios -------------------------------------------------

def concurrent_ventilator(sched):
    """Two seeded epochs over three items through a real
    :class:`~petastorm_tpu.workers.ventilator.ConcurrentVentilator` with a
    tight in-flight budget, a checkpoint snapshot mid-stream, and a
    consumer thread doing delivery + completion callbacks."""
    from petastorm_tpu.workers.ventilator import ConcurrentVentilator

    got = []
    cv = threading.Condition()

    def ventilate(_seq=None, **item):
        with cv:
            got.append(_seq)
            cv.notify_all()

    vent = ConcurrentVentilator(ventilate, [{'i': k} for k in range(3)],
                                iterations=2, max_ventilation_queue_size=2,
                                randomize_item_order=True, random_seed=7,
                                tag_items=True)
    sched.track(vent, name='ConcurrentVentilator')
    vent.start()
    expected = 6
    for n in range(expected):
        with cv:
            while not got:
                cv.wait()
            seq = got.pop(0)
        vent.mark_delivered(seq)
        vent.processed_item(seq)
        if n == 2:
            state = vent.state_dict()
            assert isinstance(state['replay_indices'], list)
    vent.stop()
    assert vent.completed()


def fair_share_ventilator(sched):
    """Two tenants (weights 2:1, per-tenant budget 1) through a real
    :class:`~petastorm_tpu.workers.ventilator.FairShareVentilator`;
    completion callbacks must fire exactly once per tenant."""
    from petastorm_tpu.workers.ventilator import FairShareVentilator

    got = []
    done = []
    cv = threading.Condition()

    def ventilate(_seq=None, **item):
        with cv:
            got.append(_seq)
            cv.notify_all()

    fsv = FairShareVentilator(ventilate, on_tenant_done=done.append)
    sched.track(fsv, name='FairShareVentilator')
    fsv.add_tenant('a', [{'x': 1}, {'x': 2}], iterations=1, weight=2,
                   max_in_flight=1)
    fsv.add_tenant('b', [{'y': 1}], iterations=1, weight=1, max_in_flight=1)
    for tq in list(fsv._tenants.values()):
        sched.track(tq, name='TenantQueue:{}'.format(tq.tenant_id))
    fsv.start()
    for _ in range(3):
        with cv:
            while not got:
                cv.wait()
            seq = got.pop(0)
        fsv.processed_item(seq)
    fsv.stop()
    assert sorted(done) == ['a', 'b'], done
    stats = fsv.tenant_stats()
    assert stats['a']['completed'] == 2 and stats['b']['completed'] == 1


def shuffling_buffer(sched):
    """Producer/consumer over a real
    :class:`~petastorm_tpu.shuffling_buffer.RandomShufflingBuffer` under the
    loader's serialization contract (one shared condition lock) — proves the
    documented usage pattern is race-free."""
    from petastorm_tpu.shuffling_buffer import RandomShufflingBuffer

    buf = sched.track(RandomShufflingBuffer(4, 1, extra_capacity=100, seed=3),
                      name='RandomShufflingBuffer')
    cv = threading.Condition()

    def producer():
        for chunk in ([0, 1, 2], [3, 4], [5]):
            with cv:
                buf.add_many(chunk)
                cv.notify_all()
        with cv:
            buf.finish()
            cv.notify_all()

    t = threading.Thread(target=producer, name='producer')
    t.start()
    retrieved = []
    while True:
        with cv:
            while not buf.can_retrieve():
                if buf._done_adding and buf.size == 0:
                    break
                cv.wait()
            if not buf.can_retrieve():
                break
            retrieved.append(buf.retrieve())
    t.join()
    assert sorted(retrieved) == list(range(6)), retrieved


def slot_registry(sched):
    """Borrow/reclaim churn on a real
    :class:`~petastorm_tpu.native.lifetime.SlotRegistry`: two borrower
    threads plus a reclaimer racing ``try_reclaim`` against the drops; the
    release callback must fire exactly once and counters must balance."""
    from petastorm_tpu.native.lifetime import SlotRegistry

    registry = sched.track(SlotRegistry(), name='SlotRegistry')
    released = []
    release_ev = threading.Event()

    def on_release():
        released.append(1)
        release_ev.set()

    slot = registry.open_slot(on_release=on_release, label='scenario-slot')
    sched.track(slot, name='Slot')
    slot.retain()                     # main's borrow, held across the run
    held = threading.Event()
    go = threading.Event()

    def borrower():
        slot.retain()
        held.set()
        go.wait()
        slot.drop()

    def reclaimer():
        slot.try_reclaim()            # may be refused (borrows live)
        release_ev.wait()             # proven released by the last drop
        counters = registry.counters()
        assert counters['lifetime_live_borrows'] == 0, counters

    b = threading.Thread(target=borrower, name='borrower')
    r = threading.Thread(target=reclaimer, name='reclaimer')
    b.start()
    r.start()
    held.wait()
    slot.seal()
    slot.drop()
    go.set()
    b.join()
    r.join()
    assert released == [1], released
    assert registry.live_borrows() == 0


class _SlotPool(object):
    """Duck-typed worker pool for the autotune actuator path: just the
    surface :class:`~petastorm_tpu.autotune.controller.Autotuner` actuates
    (``workers_count`` + grow/retire), state under its own lock."""

    def __init__(self, workers=2):
        self._lock = threading.Lock()
        self.workers_count = workers

    def add_worker_slot(self):
        with self._lock:
            self.workers_count += 1
            return self.workers_count

    def retire_worker_slot(self):
        with self._lock:
            self.workers_count -= 1
            return self.workers_count


def autotune_actuator(sched):
    """The ISSUE's motivating edge: autotuner actuation
    (``pool.add_worker_slot`` then ``ventilator.set_max_queue_size``)
    running concurrently with the ventilator's feeding thread and the
    consumer's completion callbacks."""
    from petastorm_tpu.autotune.controller import Autotuner, AutotuneConfig
    from petastorm_tpu.workers.ventilator import ConcurrentVentilator

    got = []
    cv = threading.Condition()

    def ventilate(_seq=None, **item):
        with cv:
            got.append(_seq)
            cv.notify_all()

    pool = sched.track(_SlotPool(workers=2), name='SlotPool')
    vent = ConcurrentVentilator(ventilate, [{'i': k} for k in range(3)],
                                iterations=1, max_ventilation_queue_size=1,
                                tag_items=True)
    sched.track(vent, name='ConcurrentVentilator')
    tuner = Autotuner(AutotuneConfig(interval_s=0.5, min_workers=1,
                                     max_workers=8),
                      pool=pool, ventilator=vent)
    sched.track(tuner, name='Autotuner')
    report = {'bottleneck': 'decode', 'stages': {'decode': 8.0, 'read': 2.0},
              'reader_wait_fraction': 0.6, 'wait_proxy': 0.6}
    window = {'window_s': 1.0, 'rows_per_s': 100.0}
    records = []

    def controller():
        # two grow actuations with hysteresis-clearing timestamps; each one
        # bumps the pool then retargets the ventilator's in-flight budget
        records.append(tuner._grow_workers(report, window, now=100.0))
        records.append(tuner._grow_workers(report, window, now=200.0))

    vent.start()
    actuator = threading.Thread(target=controller, name='actuator')
    actuator.start()
    for _ in range(3):
        with cv:
            while not got:
                cv.wait()
            seq = got.pop(0)
        vent.mark_delivered(seq)
        vent.processed_item(seq)
    actuator.join()
    vent.stop()
    assert pool.workers_count == 4, pool.workers_count
    assert records[0] is not None and records[1] is not None
    assert len(tuner.decision_records()) == 2


#: real components — tier-1 requires every entry to pass exploration
SCENARIOS = {
    'concurrent_ventilator': concurrent_ventilator,
    'fair_share_ventilator': fair_share_ventilator,
    'shuffling_buffer': shuffling_buffer,
    'slot_registry': slot_registry,
    'autotune_actuator': autotune_actuator,
}

#: seeded defects — the explorer must catch every entry
DEFECT_SCENARIOS = {
    'torn_counter': torn_counter,
    'safe_counter': safe_counter,   # the race-free twin (soundness control)
    'abba_deadlock': abba_deadlock,
    'prefix_ventilator_flags': prefix_ventilator_flags,
}


def lookup(name):
    """Resolve a scenario by name across both registries."""
    fn = SCENARIOS.get(name) or DEFECT_SCENARIOS.get(name)
    if fn is None:
        raise KeyError(name)
    return fn


__all__ = ['DEFECT_SCENARIOS', 'SCENARIOS', 'SafeCounter', 'TornCounter',
           'abba_deadlock', 'autotune_actuator', 'concurrent_ventilator',
           'fair_share_ventilator', 'lookup', 'prefix_ventilator_flags',
           'safe_counter', 'shuffling_buffer', 'slot_registry',
           'torn_counter']
