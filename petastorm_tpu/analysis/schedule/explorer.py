"""Schedule exploration: N seeded random schedules + bounded-preemption DFS.

The exploration contract (`docs/analysis.md`):

1. **Random phase** — ``schedules`` runs, each driven by
   ``RandomStrategy(seed + i)``.  Reproducible: the same seed explores the
   same schedules in the same order.
2. **DFS phase** — iterative-context-bounding over choice prefixes: each
   completed run contributes branch points (step, alternative thread), and
   a branch is explored only while its cumulative *preemption* count (a
   switch away from a thread that could have kept running) stays within
   ``max_preemptions``.  Small preemption bounds find most real concurrency
   bugs (the CHESS observation) while keeping the state space tractable.
3. **Replay** — when :data:`~petastorm_tpu.analysis.schedule.scheduler.SCHEDULE_ENV`
   (``PSTPU_SCHEDULE``) is set, exploration is skipped and exactly that
   schedule runs, byte-for-byte.  Every failure report prints its schedule
   string so this is a copy-paste away.

A run *fails* on a detected race, a deadlock, or a thread exception; it is
*inconclusive* when the step budget runs out or a replayed schedule no
longer matches the code.  Both stop the exploration immediately — the
report carries the offending :class:`RunResult`.
"""

from __future__ import annotations

import os

from petastorm_tpu.analysis.schedule.scheduler import (PrefixStrategy,
                                                       RandomStrategy,
                                                       ReplayStrategy,
                                                       Scheduler,
                                                       schedule_from_env)

#: cap on queued-but-unexplored DFS branches (memory guard; hitting it is
#: logged in the report, never silent)
_MAX_PENDING = 20000


class ExploreReport(object):
    """Outcome of one :func:`explore` call over a single scenario."""

    __slots__ = ('scenario', 'schedules_run', 'random_runs', 'dfs_runs',
                 'failure', 'replayed', 'dfs_truncated')

    def __init__(self, scenario):
        self.scenario = scenario
        self.schedules_run = 0
        self.random_runs = 0
        self.dfs_runs = 0
        self.failure = None      # the first failing/inconclusive RunResult
        self.replayed = False    # PSTPU_SCHEDULE drove a single replay
        self.dfs_truncated = False

    @property
    def ok(self):
        return self.failure is None

    def describe(self):
        if self.failure is None:
            extra = ' [DFS frontier truncated]' if self.dfs_truncated else ''
            return ('{}: ok ({} schedules: {} random + {} DFS){}'.format(
                self.scenario, self.schedules_run, self.random_runs,
                self.dfs_runs, extra))
        return ('{}: FAILED after {} schedules\n{}\nreplay with: '
                'PSTPU_SCHEDULE={}'.format(
                    self.scenario, self.schedules_run,
                    self.failure.describe(), self.failure.schedule))


def run_one(scenario_fn, strategy, max_steps=20000):
    """One scheduled run of ``scenario_fn`` under ``strategy``; the scenario
    receives the :class:`Scheduler` (for ``track``/``yield_now``)."""
    sched = Scheduler(strategy=strategy, max_steps=max_steps)
    result = sched.run(lambda: scenario_fn(sched))
    return sched, result


def _preemption_costs(decisions):
    """Cumulative preemption count *before* each decision.  A preemption is
    choosing a thread other than the previous one while the previous one was
    still in the runnable set."""
    costs = []
    total = 0
    for runnable, chosen, prev in decisions:
        costs.append(total)
        if prev is not None and prev in runnable and chosen != prev:
            total += 1
    return costs


def explore(scenario_fn, name='scenario', schedules=300, seed=0,
            dfs_budget=100, max_preemptions=2, max_steps=20000,
            environ=os.environ):
    """Explore ``scenario_fn`` and return an :class:`ExploreReport`.

    Stops at the first failure (its schedule string is the repro).  With
    ``PSTPU_SCHEDULE`` set in ``environ``, runs exactly that schedule once.
    """
    report = ExploreReport(name)

    env_schedule = schedule_from_env(environ)
    if env_schedule is not None:
        report.replayed = True
        _sched, result = run_one(scenario_fn, ReplayStrategy(env_schedule),
                                 max_steps)
        report.schedules_run = 1
        if not result.ok:
            report.failure = result
        return report

    # phase 1: seeded random schedules
    for i in range(schedules):
        _sched, result = run_one(scenario_fn, RandomStrategy(seed + i),
                                 max_steps)
        report.schedules_run += 1
        report.random_runs += 1
        if not result.ok:
            report.failure = result
            return report

    # phase 2: bounded-preemption DFS over choice prefixes
    pending = [()]
    seen = {()}
    while pending and report.dfs_runs < dfs_budget:
        prefix = pending.pop()
        sched, result = run_one(scenario_fn, PrefixStrategy(prefix),
                                max_steps)
        report.schedules_run += 1
        report.dfs_runs += 1
        if not result.ok:
            report.failure = result
            return report
        decisions = sched.decisions
        costs = _preemption_costs(decisions)
        trace = [chosen for _r, chosen, _p in decisions]
        # branch only past the forced prefix: earlier steps were explored
        # when their own prefixes were generated
        for i in range(len(prefix), len(decisions)):
            runnable, chosen, prev = decisions[i]
            for alt in runnable:
                if alt == chosen:
                    continue
                cost = costs[i] + (1 if prev is not None and prev in runnable
                                   and alt != prev else 0)
                if cost > max_preemptions:
                    continue
                branch = tuple(trace[:i]) + (alt,)
                if branch in seen:
                    continue
                if len(pending) >= _MAX_PENDING:
                    report.dfs_truncated = True
                    break
                seen.add(branch)
                pending.append(branch)
    return report


def replay(scenario_fn, schedule, max_steps=20000):
    """Replay one recorded schedule (a string or an index list) and return
    its :class:`RunResult` — the regression-test entry point."""
    if isinstance(schedule, str):
        from petastorm_tpu.analysis.schedule.scheduler import parse_schedule
        schedule = parse_schedule(schedule)
    _sched, result = run_one(scenario_fn, ReplayStrategy(schedule), max_steps)
    return result


__all__ = ['ExploreReport', 'explore', 'replay', 'run_one']
