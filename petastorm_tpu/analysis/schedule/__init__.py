"""Deterministic interleaving exploration for the thread plane.

The dynamic half of PR 16's race story (static half:
:mod:`petastorm_tpu.analysis.races`, rules PT1300-PT1303):

* :mod:`~petastorm_tpu.analysis.schedule.scheduler` — the loom-style
  cooperative scheduler (patched ``threading`` primitives, seeded +
  replayable schedules, vector-clock race detection, deadlock detection);
* :mod:`~petastorm_tpu.analysis.schedule.explorer` — N random schedules +
  bounded-preemption DFS, with ``PSTPU_SCHEDULE=`` byte-for-byte replay;
* :mod:`~petastorm_tpu.analysis.schedule.scenarios` — the real-component
  scenarios tier-1 explores, plus seeded-defect fixtures proving the
  explorer has teeth;
* :mod:`~petastorm_tpu.analysis.schedule.cli` — ``petastorm-tpu-race``.

See docs/analysis.md ("reading a schedule trace") for how to act on a
failure report.
"""

from petastorm_tpu.analysis.schedule.explorer import (ExploreReport, explore,
                                                      replay, run_one)
from petastorm_tpu.analysis.schedule.scenarios import (DEFECT_SCENARIOS,
                                                       SCENARIOS, lookup)
from petastorm_tpu.analysis.schedule.scheduler import (SCHEDULE_ENV,
                                                       PrefixStrategy, Race,
                                                       RandomStrategy,
                                                       ReplayStrategy,
                                                       RunResult,
                                                       ScheduleDivergence,
                                                       Scheduler,
                                                       SchedulerError,
                                                       current_scheduler,
                                                       parse_schedule,
                                                       schedule_from_env)

__all__ = [
    'DEFECT_SCENARIOS', 'ExploreReport', 'PrefixStrategy', 'Race',
    'RandomStrategy', 'ReplayStrategy', 'RunResult', 'SCENARIOS',
    'SCHEDULE_ENV', 'ScheduleDivergence', 'Scheduler', 'SchedulerError',
    'current_scheduler', 'explore', 'lookup', 'parse_schedule', 'replay',
    'run_one', 'schedule_from_env',
]
