"""``petastorm-tpu-race``: one front door for both halves of the race story.

::

    petastorm-tpu-race explore [scenario ...] [options]   # dynamic half
    petastorm-tpu-race lint [paths ...] [options]         # static half
    petastorm-tpu-race list                               # scenario catalog

``explore`` runs the deterministic-schedule explorer over the named
scenarios (default: every real-component scenario).  A failure prints the
race/deadlock report plus its schedule string; re-running with
``PSTPU_SCHEDULE=<string>`` (and exactly one scenario) replays that
interleaving byte-for-byte.

``lint`` is the whole-program static pass: it delegates to
``petastorm-tpu-lint --select PT13`` so only the concurrency family
(PT1300-PT1303) reports, with every lint flag (``--format sarif``,
``--changed``, ``--cache``, ...) passed through.

Exit-code contract (stable; scripts and CI may rely on it):

* ``0`` — clean: every explored scenario passed / no open PT13xx findings.
* ``1`` — a finding: a data race, a deadlock, a scenario invariant
  failure, or an open static finding.
* ``2`` — usage error: unknown scenario/option, or ``PSTPU_SCHEDULE`` with
  zero or several scenarios.
* ``3`` — inconclusive: the step budget ran out, a replayed schedule
  diverged from the code, or a thread stalled outside the instrumentation
  — the component is neither proven nor disproven; fix the scenario or
  raise the budget.
"""

from __future__ import annotations

import argparse
import os
import sys

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2
EXIT_INCONCLUSIVE = 3


def build_parser():
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-race',
        description='Thread-plane race tooling: deterministic interleaving '
                    'exploration (explore) and whole-program lockset lints '
                    'PT1300-PT1303 (lint). See docs/analysis.md.')
    sub = parser.add_subparsers(dest='mode')

    explore_p = sub.add_parser(
        'explore', help='run scenarios under the deterministic scheduler')
    explore_p.add_argument('scenarios', nargs='*',
                           help='scenario names (default: every '
                                'real-component scenario; see "list")')
    explore_p.add_argument('--schedules', type=int, default=300,
                           help='random schedules per scenario '
                                '(default: 300)')
    explore_p.add_argument('--seed', type=int, default=0,
                           help='base RNG seed (schedule i uses seed+i)')
    explore_p.add_argument('--dfs-budget', type=int, default=100,
                           help='bounded-preemption DFS runs per scenario '
                                '(default: 100)')
    explore_p.add_argument('--max-preemptions', type=int, default=2,
                           help='DFS preemption bound (default: 2)')
    explore_p.add_argument('--max-steps', type=int, default=20000,
                           help='per-run scheduling-step budget')

    sub.add_parser('list', help='list the scenario catalog')

    lint_p = sub.add_parser(
        'lint', help='run the PT13xx whole-program lints '
                     '(petastorm-tpu-lint --select PT13 passthrough)')
    lint_p.add_argument('args', nargs=argparse.REMAINDER,
                        help='paths and petastorm-tpu-lint options')
    return parser


def _cmd_list():
    from petastorm_tpu.analysis.schedule.scenarios import (DEFECT_SCENARIOS,
                                                           SCENARIOS)
    print('real-component scenarios (explored by default):')
    for name, fn in sorted(SCENARIOS.items()):
        doc = (fn.__doc__ or '').strip().split('\n')[0]
        print('  {:<24} {}'.format(name, doc))
    print('seeded-defect fixtures (run by explicit name only):')
    for name, fn in sorted(DEFECT_SCENARIOS.items()):
        doc = (fn.__doc__ or '').strip().split('\n')[0]
        print('  {:<24} {}'.format(name, doc))
    return EXIT_CLEAN


def _cmd_explore(args):
    from petastorm_tpu.analysis.schedule.explorer import explore
    from petastorm_tpu.analysis.schedule.scenarios import SCENARIOS, lookup
    from petastorm_tpu.analysis.schedule.scheduler import SCHEDULE_ENV

    names = args.scenarios or sorted(SCENARIOS)
    targets = []
    for name in names:
        try:
            targets.append((name, lookup(name)))
        except KeyError:
            print('error: unknown scenario {!r} (see "petastorm-tpu-race '
                  'list")'.format(name), file=sys.stderr)
            return EXIT_USAGE
    if os.environ.get(SCHEDULE_ENV) and len(targets) != 1:
        print('error: {} replay needs exactly one scenario, got {}'.format(
            SCHEDULE_ENV, len(targets)), file=sys.stderr)
        return EXIT_USAGE

    worst = EXIT_CLEAN
    for name, fn in targets:
        report = explore(fn, name=name, schedules=args.schedules,
                         seed=args.seed, dfs_budget=args.dfs_budget,
                         max_preemptions=args.max_preemptions,
                         max_steps=args.max_steps)
        print(report.describe())
        if report.failure is not None:
            rc = (EXIT_INCONCLUSIVE if report.failure.inconclusive
                  and not report.failure.races
                  and report.failure.deadlock is None
                  and not report.failure.errors
                  else EXIT_FINDINGS)
            worst = max(worst, rc)
    return worst


def _cmd_lint(raw_args):
    from petastorm_tpu.analysis.cli import main as lint_main
    return lint_main(['--select', 'PT13'] + list(raw_args))


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.mode == 'list':
        return _cmd_list()
    if args.mode == 'explore':
        return _cmd_explore(args)
    if args.mode == 'lint':
        return _cmd_lint(args.args)
    build_parser().print_help()
    return EXIT_USAGE


if __name__ == '__main__':
    sys.exit(main())
