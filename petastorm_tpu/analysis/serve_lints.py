"""PT1000 — serve actuator discipline.

The serve daemon is a LONG-LIVED multi-tenant process whose admission /
eviction / detach decisions change other processes' behavior at a distance:
an eviction kills a training job's input stream, an admit changes everyone's
fair share. The debugging story for "my consumer was evicted — why?"
(``docs/troubleshooting.md``) is the daemon's trace ring, which only works if
every actuation leaves a span there naming the tenant it acted on. This rule
makes that discipline mechanical (the serve-plane analog of PT702):

* every call to a serve **actuator** — broadcast-ring slot operations
  (``<x>.ring.join()``, ``evict``, ``leave``) and scheduler tenancy
  operations (``add_tenant``, ``remove_tenant``) — inside
  ``petastorm_tpu/serve/`` must sit lexically inside a ``with obs.span(...)``
  (or ``stage(...)``) block **whose span carries a ``tenant=`` argument**, so
  the decision lands in the trace next to the tenant it affected.

The rule scopes to the serve package only: the primitives themselves are
defined in ``native/shm_ring.py`` / ``workers/ventilator.py`` and are called
freely by tests; the discipline binds the daemon, the one caller that
actuates autonomously against other processes.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, add_parents, attr_chain, walk_functions

#: method names that are serve actuators wherever they appear in serve/
_ACTUATORS = frozenset({'evict', 'leave', 'add_tenant', 'remove_tenant'})

#: span-context callables that satisfy the wrapping requirement
_SPAN_OPENERS = frozenset({'span', 'stage', 'decision_span'})


def _call_name(call):
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_actuator(call):
    name = _call_name(call)
    if name in _ACTUATORS:
        return name
    if name == 'join':
        # only broadcast-ring joins (x.ring.join()) — never thread/pool joins
        chain = attr_chain(call.func) or ''
        if chain.endswith('.ring.join') or chain == 'ring.join':
            return 'ring.join'
    return None


def _tenant_span_around(node, stop_at):
    """Is ``node`` lexically inside a ``with`` opening a span that carries a
    ``tenant=`` keyword, before ``stop_at``?"""
    cur = node
    while cur is not None and cur is not stop_at:
        parent = getattr(cur, 'pt_parent', None)
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            for item in parent.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and _call_name(expr) in _SPAN_OPENERS \
                        and any(kw.arg == 'tenant' for kw in expr.keywords):
                    return True
        cur = parent
    return False


class ServeActuatorChecker(Checker):
    code = 'PT1000'
    name = 'serve-actuator-discipline'
    description = ('serve-path actuators (admit/evict/detach: ring.join, '
                   'evict, leave, add_tenant, remove_tenant) must run inside '
                   'a traced span carrying the tenant id — an unexplained '
                   'eviction is an undebuggable one')
    scope = ('*serve/*.py',)

    def check(self, src):
        add_parents(src.tree)
        for func, _cls in walk_functions(src.tree):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = _is_actuator(node)
                if name is None:
                    continue
                if not _tenant_span_around(node, func):
                    yield self.finding(
                        src, node.lineno,
                        '{}() called outside a tenant-tagged span: wrap the '
                        'actuation in `with obs.span(..., tenant=<id>)` so the '
                        'decision is reconstructable from the daemon trace'
                        .format(name))
