"""PT1200 — elastic shard maps must be deterministic.

The whole elastic-sharding design rests on one property: every host, given
the same ``(seed, epoch, member set)``, computes the SAME shard map without
talking to anyone (``docs/parallelism.md``, "Elastic pod sharding").  There
is no leader to arbitrate a disagreement — two hosts that derive different
maps for the same generation silently double-read or drop row groups, and
nothing downstream can detect it.  Determinism is therefore not a style
preference in :mod:`petastorm_tpu.elastic.shardmap`; it is the correctness
argument, and its failure modes are lexically checkable:

* **wall-clock reads** (``time.time()``, ``datetime.now()``, …) — two hosts
  never read the same clock, so any clock-derived value diverges the maps;
* **unseeded randomness** — module-global RNG calls (``random.random()``,
  ``np.random.shuffle(...)``) and RNG constructors without an explicit seed
  (``default_rng()``, ``Random()``, ``RandomState(None)``) give each host a
  private stream.  Seeded constructors are fine: deriving the permutation
  from ``default_rng(stable_hash(...))`` is exactly the intended pattern;
* **set-iteration-order dependence** — iterating a ``set``/``frozenset``
  (or materializing one with ``list(set(...))``) bakes hash-table order
  into the map, which varies across processes under hash randomization.
  Wrap the set in ``sorted(...)`` to fix an order first.

The rule scopes to the shard-map module only: membership tracking
legitimately reads wall clocks (lease freshness IS a clock comparison) and
the coordinator stamps telemetry — the purity requirement applies to the
one module whose outputs every host must agree on bit-for-bit.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import (Checker, add_parents, attr_chain,
                                         walk_functions)

#: dotted call chains that read a wall clock
_WALL_CLOCK = frozenset({
    'time.time', 'time.time_ns', 'time.monotonic', 'time.monotonic_ns',
    'time.perf_counter', 'time.perf_counter_ns', 'time.clock_gettime',
    'datetime.now', 'datetime.utcnow', 'datetime.today',
    'datetime.datetime.now', 'datetime.datetime.utcnow',
    'datetime.datetime.today', 'datetime.date.today', 'date.today',
})

#: module-global RNG entry points: a stream shared per-process, never per-pod
_GLOBAL_RNG = frozenset({
    'random.random', 'random.randint', 'random.randrange', 'random.choice',
    'random.choices', 'random.sample', 'random.shuffle', 'random.uniform',
    'random.seed', 'random.getrandbits',
})

#: np.random module-level functions are the legacy global stream
_NP_RANDOM_PREFIXES = ('np.random.', 'numpy.random.')

#: RNG constructors that take the seed as their first argument
_SEEDED_CTORS = frozenset({'default_rng', 'Random', 'RandomState',
                           'SystemRandom', 'Generator', 'PCG64', 'Philox'})

#: np.random constructors reachable through the module chain
_NP_CTOR_CHAINS = frozenset({
    'np.random.default_rng', 'numpy.random.default_rng',
    'np.random.RandomState', 'numpy.random.RandomState',
    'np.random.Generator', 'numpy.random.Generator',
    'random.Random', 'random.SystemRandom',
})

#: builtins that materialize an iteration over their (set-typed) argument in
#: hash order (min/max/sum stay allowed: their values are order-independent)
_ORDER_SENSITIVE_WRAPPERS = frozenset({'list', 'tuple', 'enumerate', 'iter'})


def _call_chain(call):
    """Dotted chain of a Call's func ('np.random.default_rng') or None."""
    return attr_chain(call.func)


def _tail(chain):
    return chain.rsplit('.', 1)[-1] if chain else None


def _is_set_expr(node):
    """Does ``node`` syntactically produce a set/frozenset?"""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = _call_chain(node)
        if chain in ('set', 'frozenset'):
            return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        # set algebra propagates set-ness from either operand
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


def _unseeded_ctor(call, chain):
    """A known RNG constructor called with no seed (or an explicit None)."""
    tail = _tail(chain)
    if tail not in _SEEDED_CTORS:
        return False
    if chain not in _NP_CTOR_CHAINS and tail not in ('default_rng',):
        # bare Random()/RandomState() names only count when imported from a
        # random module — we can't resolve imports, so accept the tail match
        # for the unambiguous constructor names and the full-chain forms.
        if tail not in ('Random', 'RandomState', 'SystemRandom'):
            return False
    if not call.args and not call.keywords:
        return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is None:
        return True
    for kw in call.keywords:
        if kw.arg == 'seed' and isinstance(kw.value, ast.Constant) \
                and kw.value.value is None:
            return True
    return False


class ElasticDeterminismChecker(Checker):
    code = 'PT1200'
    name = 'elastic-shardmap-determinism'
    description = ('shard-map construction must be a pure function of '
                   '(seed, epoch, members): wall-clock reads, unseeded '
                   'randomness and set-iteration-order dependence diverge '
                   'the maps across hosts')
    scope = ('*elastic/shardmap*.py',)

    def check(self, src):
        add_parents(src.tree)
        for func, _cls in walk_functions(src.tree):
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    for finding in self._check_call(src, node):
                        yield finding
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if _is_set_expr(node.iter):
                        yield self.finding(
                            src, node.lineno,
                            'iterating a set directly: hash order differs '
                            'across processes, so the derived shard map '
                            'would too — wrap the set in sorted(...)')
                elif isinstance(node, ast.comprehension):
                    if _is_set_expr(node.iter):
                        yield self.finding(
                            src, node.iter.lineno,
                            'comprehension iterates a set directly: hash '
                            'order differs across processes — wrap the set '
                            'in sorted(...)')

    def _check_call(self, src, call):
        chain = _call_chain(call)
        if chain is None:
            return
        if chain in _WALL_CLOCK:
            yield self.finding(
                src, call.lineno,
                '{}() reads a wall clock: no two hosts see the same value, '
                'so clock-derived shard maps diverge — derive everything '
                'from (seed, epoch, members)'.format(chain))
            return
        if chain in _GLOBAL_RNG or any(
                chain.startswith(p) and _tail(chain) not in _SEEDED_CTORS
                for p in _NP_RANDOM_PREFIXES):
            yield self.finding(
                src, call.lineno,
                '{}() draws from the process-global RNG stream: each host '
                'gets a private sequence — construct a generator seeded '
                'from stable_hash(seed, epoch, ...)'.format(chain))
            return
        if _unseeded_ctor(call, chain):
            yield self.finding(
                src, call.lineno,
                '{}() constructed without an explicit seed: the OS entropy '
                'default gives every host a different stream — pass a seed '
                'derived from stable_hash(...)'.format(chain))
            return
        tail = _tail(chain)
        if tail in _ORDER_SENSITIVE_WRAPPERS and call.args \
                and _is_set_expr(call.args[0]):
            yield self.finding(
                src, call.lineno,
                '{}(set(...)) bakes hash-table iteration order into the '
                'result: order varies across processes under hash '
                'randomization — use sorted(...) instead'.format(tail))
