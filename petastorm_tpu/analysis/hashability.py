"""PT600 — ``__eq__`` without ``__hash__``.

Python sets ``__hash__ = None`` on any class that defines ``__eq__`` without
also defining ``__hash__`` — the class (and anything embedding it, e.g. a
``pyarrow.fs.PyFileSystem`` wrapping a handler) silently becomes unhashable.
The round-5 ``RetryingHandler`` defect is this exact class of bug: adding a
policy-aware ``__eq__`` for pyarrow's filesystem dedupe broke every caller
that keys a dict/set on the filesystem. Intentional unhashability must be
explicit (``__hash__ = None`` in the class body); everything else needs a
``__hash__`` consistent with its ``__eq__``.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker


class HashabilityChecker(Checker):
    code = 'PT600'
    name = 'hashability'
    description = '__eq__ defined without __hash__ (class silently unhashable)'
    scope = ('*.py',)

    def check(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            has_eq = eq_line = None
            has_hash = False
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if item.name == '__eq__':
                        has_eq, eq_line = True, item.lineno
                    elif item.name == '__hash__':
                        has_hash = True
                elif isinstance(item, ast.Assign):
                    # `__hash__ = None` (explicit unhashable) or an alias
                    if any(isinstance(t, ast.Name) and t.id == '__hash__'
                           for t in item.targets):
                        has_hash = True
            if has_eq and not has_hash:
                yield self.finding(
                    src, eq_line,
                    'class {} defines __eq__ without __hash__ — Python sets '
                    '__hash__ = None, making it (and any wrapper like '
                    'pyarrow.fs.PyFileSystem) unhashable; add a consistent '
                    '__hash__, or an explicit __hash__ = None if intended'.format(
                        node.name))
