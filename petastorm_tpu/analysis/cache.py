"""Incremental runs for the linter: ``--changed`` file selection and a
per-file result cache.

The full pass re-parses and re-walks every file on every invocation; in the
edit loop that is almost all wasted work — a file's findings depend only on
inputs that rarely change. This module makes the dependency set explicit and
keys a result cache on it.

**Cache key** (the invalidation contract; also documented in
docs/analysis.md):

* the file's root-relative path and its content bytes (a rename or edit is a
  new key — renames matter because ``scope`` patterns and noqa semantics
  match on the path);
* the content of every sibling ``*.cpp``/``*.cc`` in the file's directory —
  the ABI/C++ conformance passes (PT90x) check a Python file *against* its
  native sources, so editing ``shm_ring.cpp`` must invalidate
  ``shm_ring.py``'s entry even though its bytes are unchanged;
* a fingerprint of the ``petastorm_tpu.analysis`` package itself (every
  ``.py`` under it, including ``protocol/``) — editing any checker, or this
  module, flushes the whole cache.

A per-path ``(mtime_ns, size)`` index short-circuits the content hash for
untouched files, so a warm no-op run does one ``stat`` per file. The index
is advisory only: a stale index entry can at worst cause a re-hash, never a
stale result, because the entry files themselves are addressed by content
key.

**What is stored**: the file's findings with ``keep_suppressed=True`` and NO
baseline applied. Baseline absorption and ``--select``/``--ignore`` are view
filters over the analysis, not part of it — they are re-applied on every
run, so switching flags never needs a re-scan and never poisons the cache.

**The whole-program pass** (the PT13xx race lints) does not fit per-file
caching — its findings depend on every in-scope file at once. It gets one
content-addressed entry instead, keyed by :func:`program_pass_key` (the
analysis fingerprint plus relpath+bytes of every file matching the program
checkers' scope). A warm run costs one hash sweep over the scoped files and
one JSON read; a ``--changed`` run passes the full listing via
``program_entries`` so cross-module properties are never derived from a
subset.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess

from petastorm_tpu.analysis.core import Finding, SourceFile, run_checkers

_SOURCE_EXTS = ('.py', '.cpp', '.cc')
_INDEX_NAME = 'index.json'


# -- file selection ---------------------------------------------------------

def iter_file_entries(paths):
    """``[(abspath, relpath)]`` for every source file under ``paths`` —
    the same listing :func:`core.collect_sources` loads, without reading
    the files."""
    from petastorm_tpu.analysis.core import _SKIP_DIRS
    entries = []
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            entries.append((root, os.path.basename(root)))
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(_SOURCE_EXTS):
                    full = os.path.join(dirpath, fn)
                    entries.append((full, os.path.relpath(full, root)))
    return entries


def changed_file_entries(paths):
    """The subset of :func:`iter_file_entries` that git considers changed:
    tracked files differing from HEAD (staged or not) plus untracked
    non-ignored files. Relpaths stay relative to the matching scan root, so
    scope patterns, noqa reporting, and baseline paths behave exactly as in
    a full run. Raises ``RuntimeError`` outside a work tree."""
    try:
        out = subprocess.run(
            ['git', 'rev-parse', '--show-toplevel'],
            capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError) as e:
        raise RuntimeError('--changed needs a git work tree: {}'.format(e))
    top = out.stdout.strip()
    changed = set()
    for cmd in (['git', '-C', top, 'diff', '--name-only', 'HEAD', '--'],
                ['git', '-C', top, 'ls-files', '--others',
                 '--exclude-standard']):
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode == 0:
            changed.update(line.strip() for line in res.stdout.splitlines()
                           if line.strip())
    changed_abs = {os.path.abspath(os.path.join(top, p)) for p in changed}
    return [(full, rel) for full, rel in iter_file_entries(paths)
            if full in changed_abs]


# -- the keying scheme ------------------------------------------------------

_fingerprint_memo = {}


def analysis_fingerprint():
    """sha256 over every ``.py`` source of the analysis package (sorted
    relpath + bytes). Memoized per process; editing any checker produces a
    new fingerprint and therefore a cold cache."""
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    if pkg_dir in _fingerprint_memo:
        return _fingerprint_memo[pkg_dir]
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
        for fn in sorted(filenames):
            if not fn.endswith('.py'):
                continue
            full = os.path.join(dirpath, fn)
            h.update(os.path.relpath(full, pkg_dir).encode())
            with open(full, 'rb') as f:
                h.update(f.read())
    _fingerprint_memo[pkg_dir] = h.hexdigest()
    return _fingerprint_memo[pkg_dir]


def _sibling_native_digest(dirpath, memo):
    """sha256 over the ``*.cpp``/``*.cc`` sources in ``dirpath`` (the PT90x
    conformance inputs of any Python file living there)."""
    if dirpath in memo:
        return memo[dirpath]
    h = hashlib.sha256()
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        names = []
    for fn in names:
        if fn.endswith(('.cpp', '.cc')):
            h.update(fn.encode())
            try:
                with open(os.path.join(dirpath, fn), 'rb') as f:
                    h.update(f.read())
            except OSError:
                pass
    memo[dirpath] = h.hexdigest()
    return memo[dirpath]


def file_key(abspath, relpath, sibling_memo):
    """The content-addressed cache key of one file's findings."""
    h = hashlib.sha256()
    h.update(analysis_fingerprint().encode())
    h.update(relpath.replace(os.sep, '/').encode())
    with open(abspath, 'rb') as f:
        h.update(f.read())
    h.update(_sibling_native_digest(os.path.dirname(abspath),
                                    sibling_memo).encode())
    return h.hexdigest()


# -- the cache itself -------------------------------------------------------

def _finding_from_dict(d):
    return Finding(path=d['path'], line=int(d['line']), code=d['rule'],
                   message=d['message'], snippet=d.get('snippet', ''),
                   status=d.get('status', 'open'))


class ResultCache(object):
    """Content-addressed per-file finding store under one directory.

    Layout: ``<key>.json`` holds one file's serialized findings;
    ``index.json`` maps relpath → ``(mtime_ns, size, key)`` so untouched
    files skip the content hash. Everything is advisory — deleting the
    directory is always safe and merely makes the next run cold."""

    def __init__(self, cache_dir):
        self.dir = cache_dir
        os.makedirs(cache_dir, exist_ok=True)
        self.hits = 0
        self.misses = 0
        self._sibling_memo = {}
        self._sibling_stamp_memo = {}
        self._index = {}
        self._index_dirty = False
        try:
            with open(os.path.join(cache_dir, _INDEX_NAME)) as f:
                self._index = json.load(f)
        except (OSError, ValueError):
            self._index = {}

    def _sibling_stamp(self, dirpath):
        # the fast path must go stale whenever the CONTENT key would: a
        # file's findings also depend on its sibling native sources (PT90x),
        # so their stats are part of the stamp
        if dirpath in self._sibling_stamp_memo:
            return self._sibling_stamp_memo[dirpath]
        out = []
        try:
            names = sorted(os.listdir(dirpath))
        except OSError:
            names = []
        for fn in names:
            if fn.endswith(('.cpp', '.cc')):
                try:
                    st = os.stat(os.path.join(dirpath, fn))
                    out.append([fn, st.st_mtime_ns, st.st_size])
                except OSError:
                    pass
        self._sibling_stamp_memo[dirpath] = out
        return out

    def _key_for(self, abspath, relpath):
        rel = relpath.replace(os.sep, '/')
        try:
            st = os.stat(abspath)
            stamp = [st.st_mtime_ns, st.st_size,
                     self._sibling_stamp(os.path.dirname(abspath))]
        except OSError:
            stamp = None
        entry = self._index.get(rel)
        if entry is not None and stamp is not None and entry[:3] == stamp:
            return entry[3], stamp
        return file_key(abspath, relpath, self._sibling_memo), stamp

    def lookup(self, abspath, relpath):
        """Cached findings for the file as it is NOW, or None."""
        key, stamp = self._key_for(abspath, relpath)
        try:
            with open(os.path.join(self.dir, key + '.json')) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        self.hits += 1
        self._remember(relpath, stamp, key)
        return [_finding_from_dict(d) for d in payload]

    def store(self, abspath, relpath, findings):
        key, stamp = self._key_for(abspath, relpath)
        self.misses += 1
        tmp = os.path.join(self.dir, key + '.json.tmp')
        with open(tmp, 'w') as f:
            json.dump([fi.to_dict() for fi in findings], f)
        os.replace(tmp, os.path.join(self.dir, key + '.json'))
        self._remember(relpath, stamp, key)

    def _remember(self, relpath, stamp, key):
        if stamp is not None:
            self._index[relpath.replace(os.sep, '/')] = stamp + [key]
            self._index_dirty = True

    def flush_index(self):
        if not self._index_dirty:
            return
        tmp = os.path.join(self.dir, _INDEX_NAME + '.tmp')
        with open(tmp, 'w') as f:
            json.dump(self._index, f)
        os.replace(tmp, os.path.join(self.dir, _INDEX_NAME))
        self._index_dirty = False

    # direct keyed entries — the whole-program pass addresses its result by
    # an aggregate digest rather than a single file's stamp

    def lookup_key(self, key):
        try:
            with open(os.path.join(self.dir, key + '.json')) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        self.hits += 1
        return [_finding_from_dict(d) for d in payload]

    def store_key(self, key, findings):
        self.misses += 1
        tmp = os.path.join(self.dir, key + '.json.tmp')
        with open(tmp, 'w') as f:
            json.dump([fi.to_dict() for fi in findings], f)
        os.replace(tmp, os.path.join(self.dir, key + '.json'))


# -- the incremental run ----------------------------------------------------

def program_pass_key(scoped_entries):
    """Aggregate content key of the whole-program pass: the analysis package
    fingerprint plus every in-scope file's relpath and bytes, in path order.
    Editing any scoped file — or any checker — is a new key; editing a file
    OUTSIDE the program scope leaves the entry warm."""
    h = hashlib.sha256()
    h.update(b'program-pass:')
    h.update(analysis_fingerprint().encode())
    for abspath, relpath in sorted(scoped_entries, key=lambda e: e[1]):
        h.update(relpath.replace(os.sep, '/').encode())
        try:
            with open(abspath, 'rb') as f:
                h.update(f.read())
        except OSError:
            h.update(b'<unreadable>')
    return h.hexdigest()


def run_analysis_incremental(file_entries, cache=None, baseline=None,
                             select=None, ignore=None, keep_suppressed=False,
                             program_entries=None):
    """:func:`analysis.run_analysis` semantics over an explicit
    ``[(abspath, relpath)]`` listing, optionally through a
    :class:`ResultCache`.

    Per-file checkers are strictly per-file (cross-file inputs — the sibling
    native sources — are part of the cache key), so per-file caching is
    exact, not approximate. Whole-program checkers (the PT13xx race lints)
    run once over ``program_entries`` (default: ``file_entries``) and cache
    their result under :func:`program_pass_key` — a ``--changed`` run must
    pass the FULL listing here, because cross-module properties cannot be
    derived from the changed subset alone."""
    from petastorm_tpu.analysis import ALL_CHECKERS
    checkers = [cls() for cls in ALL_CHECKERS]
    per_file = [c for c in checkers if not c.program_level]
    program = [c for c in checkers if c.program_level]
    findings = []
    for abspath, relpath in file_entries:
        cached = cache.lookup(abspath, relpath) if cache is not None else None
        if cached is None:
            src = SourceFile.load(abspath, relpath)
            cached = run_checkers(per_file, [src], keep_suppressed=True)
            if cache is not None:
                cache.store(abspath, relpath, cached)
        findings.extend(cached)
    if program:
        scoped = [(a, r) for a, r in (program_entries if program_entries
                                      is not None else file_entries)
                  if any(c.matches_path(r.replace(os.sep, '/'))
                         for c in program)]
        prog_findings = None
        key = program_pass_key(scoped) if cache is not None else None
        if cache is not None:
            prog_findings = cache.lookup_key(key)
        if prog_findings is None:
            sources = [SourceFile.load(a, r) for a, r in scoped]
            prog_findings = [f for f in run_checkers(program, sources,
                                                     keep_suppressed=True)
                             if f.code != 'PT000']   # PT000 is the per-file pass's
            if cache is not None:
                cache.store_key(key, prog_findings)
        findings.extend(prog_findings)
    if cache is not None:
        cache.flush_index()
    # the stored results are unfiltered; re-apply the view filters the same
    # way run_analysis/run_checkers do
    open_findings = sorted(f for f in findings if f.status == 'open')
    suppressed = [f for f in findings if f.status != 'open']
    if baseline is not None:
        open_findings, absorbed = baseline.split(open_findings)
        suppressed = suppressed + absorbed
    findings = sorted(open_findings + suppressed) if keep_suppressed \
        else open_findings
    if select is not None:
        prefixes = tuple(select)
        findings = [f for f in findings if f.code.startswith(prefixes)]
    if ignore is not None and tuple(ignore):
        prefixes = tuple(ignore)
        findings = [f for f in findings if not f.code.startswith(prefixes)]
    return findings


__all__ = ['ResultCache', 'analysis_fingerprint', 'changed_file_entries',
           'file_key', 'iter_file_entries', 'program_pass_key',
           'run_analysis_incremental']
