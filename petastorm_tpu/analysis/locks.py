"""PT100/PT101 — lock discipline in the concurrent data-plane modules.

**PT100** A class that guards shared state with a ``threading.Lock``/
``RLock``/``Condition`` must write that state under the lock everywhere: an
attribute is *lock-guarded* once any method writes or mutates it inside a
``with self._lock`` block, and any write to a guarded attribute outside such a
block (``__init__`` excepted — no second thread exists yet) is a torn-update
waiting for a scheduler interleaving. This is exactly the discipline the
pools/ventilator document by hand today.

**PT101** Nested lock acquisitions define a lock-order graph (edge A -> B when
B is acquired while A is held, including one level of ``self.method()``
indirection within the class). A cycle in that graph is a latent ABBA
deadlock: two threads entering from different edges block forever.

Scope: the concurrency domains named in the analysis brief — ``workers/``,
``shuffling_buffer.py``, ``cache.py``, ``reader.py`` — plus the other modules
that hold locks today (``jax/``, ``native/``, ``local_disk_cache.py``).
"""

from __future__ import annotations

import ast
from collections import defaultdict

from petastorm_tpu.analysis.core import Checker, attr_chain, class_methods

#: constructors whose result is a lock-like guard
_LOCK_FACTORIES = {'Lock', 'RLock', 'Condition', 'Semaphore', 'BoundedSemaphore'}

#: method calls that mutate their receiver in place
_MUTATORS = {'append', 'appendleft', 'add', 'clear', 'discard', 'extend',
             'insert', 'pop', 'popitem', 'popleft', 'remove', 'update',
             'setdefault', 'sort', 'reverse'}


def _is_lock_ctor(node):
    """True for ``threading.Lock()``, ``Lock()``, ``mp_ctx.RLock()``, ..."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None)
    return name in _LOCK_FACTORIES


def _self_attr(node):
    """'attr' when node is ``self.attr``, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == 'self':
        return node.attr
    return None


def _with_lock_attrs(with_node, lock_attrs):
    """Lock attributes of ``self`` acquired by a ``with`` statement."""
    acquired = []
    for item in with_node.items:
        expr = item.context_expr
        # `with self._lock:` and `with self._cv:` (Condition) both guard
        attr = _self_attr(expr)
        if attr in lock_attrs:
            acquired.append(attr)
    return acquired


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body tracking the held-locks stack. Records
    attribute writes/mutations with the lock set held at that point, direct
    ``self.m()`` calls under a lock, and nested acquisition edges."""

    def __init__(self, lock_attrs):
        self.lock_attrs = lock_attrs
        self.held = []           # stack of lock attr names
        self.writes = []         # (attr, frozenset(held), lineno, is_mutation)
        self.calls_under = []    # (method_name, frozenset(held), lineno)
        self.edges = []          # (outer_lock, inner_lock, lineno)
        self.acquired_any = False

    def visit_With(self, node):
        acquired = _with_lock_attrs(node, self.lock_attrs)
        if acquired:
            self.acquired_any = True
            for outer in self.held:
                for inner in acquired:
                    if outer != inner:
                        self.edges.append((outer, inner, node.lineno))
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()
        # with-items themselves are not re-visited: acquisition handled above

    visit_AsyncWith = visit_With

    def _record_write(self, target, lineno):
        attr = _self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)  # self.d[k] = v mutates self.d
        if attr is not None and attr not in self.lock_attrs:
            self.writes.append((attr, frozenset(self.held), lineno, False))

    def visit_Assign(self, node):
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]):
                self._record_write(el, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._record_write(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record_write(node.target, node.lineno)
            self.visit(node.value)

    def visit_Call(self, node):
        func = node.func
        if isinstance(func, ast.Attribute):
            recv_attr = _self_attr(func.value)
            if func.attr in _MUTATORS and recv_attr is not None \
                    and recv_attr not in self.lock_attrs:
                self.writes.append((recv_attr, frozenset(self.held), node.lineno, True))
            if recv_attr is None and _self_attr(func) is not None and self.held:
                # self.m(...) while holding a lock: one indirection level for
                # the lock-order graph
                self.calls_under.append((func.attr, frozenset(self.held), node.lineno))
        self.generic_visit(node)

    # nested defs/lambdas run later, possibly on another thread or lock
    # context — their writes are not attributable to the current held set
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return


class LockDisciplineChecker(Checker):
    code = 'PT100'
    codes = ('PT100', 'PT101')
    name = 'lock-discipline'
    description = ('writes to lock-guarded shared state outside "with self._lock"; '
                   'lock-acquisition-order cycles (PT101)')
    scope = ('*workers/*.py', '*shuffling_buffer.py', '*cache.py', '*reader.py',
             '*jax/*.py', '*native/*.py', '*local_disk_cache.py',
             '*chunkstore/*.py', '*fabric/*.py')

    def check(self, src):
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(src, node)

    def _check_class(self, src, classdef):
        methods = class_methods(classdef)
        lock_attrs = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            lock_attrs.add(attr)
        if not lock_attrs:
            return

        scans = {}
        for m in methods:
            scan = _MethodScan(lock_attrs)
            for stmt in m.body:
                scan.visit(stmt)
            scans[m.name] = scan

        # pass 1: attributes written/mutated at least once under a lock
        guarded = set()
        for scan in scans.values():
            for attr, held, _lineno, _mut in scan.writes:
                if held:
                    guarded.add(attr)

        # pass 2: writes to guarded attributes with no lock held
        for name, scan in scans.items():
            if name == '__init__':
                continue
            for attr, held, lineno, is_mutation in scan.writes:
                if attr in guarded and not held:
                    verb = 'mutation of' if is_mutation else 'write to'
                    yield self.finding(
                        src, lineno,
                        "{} lock-guarded attribute 'self.{}' outside a 'with' on {} "
                        '(class {})'.format(
                            verb, attr,
                            ' / '.join("'self.{}'".format(a) for a in sorted(lock_attrs)),
                            classdef.name))

        # pass 3: lock-order graph (direct nesting + one self-call indirection)
        edges = defaultdict(set)
        edge_lines = {}
        for scan in scans.values():
            for outer, inner, lineno in scan.edges:
                edges[outer].add(inner)
                edge_lines.setdefault((outer, inner), lineno)
            for callee, held, lineno in scan.calls_under:
                callee_scan = scans.get(callee)
                if callee_scan is None:
                    continue
                inner_locks = {a for _, h, _, _ in callee_scan.writes for a in h}
                for _, h, _ in callee_scan.calls_under:
                    inner_locks |= set(h)
                for outer in held:
                    for inner in inner_locks:
                        if outer != inner:
                            edges[outer].add(inner)
                            edge_lines.setdefault((outer, inner), lineno)
        for cycle in _find_cycles(edges):
            first = edge_lines.get((cycle[0], cycle[1]), classdef.lineno)
            yield self.finding(
                src, first,
                'lock-acquisition-order cycle {} in class {} — two threads entering '
                'from different edges deadlock'.format(
                    ' -> '.join("'self.{}'".format(a) for a in cycle + (cycle[0],)),
                    classdef.name),
                code='PT101')


def _find_cycles(edges):
    """Minimal distinct cycles of a small digraph, as node tuples."""
    cycles = []
    seen_cycles = set()

    def dfs(start, node, path):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                canon = tuple(path)
                rotations = {canon[i:] + canon[:i] for i in range(len(canon))}
                if not rotations & seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(canon)
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for start in sorted(edges):
        dfs(start, start, [start])
    return cycles
