"""First-party invariant linter for the petastorm_tpu codebase.

The pipeline spans five concurrency domains — thread pools, spawned
zmq/shm-ring process pools, the ventilator, the double-buffered JAX infeed,
and ctypes views over mmap'd Parquet pages — and each class of defect the
round-5 advisors surfaced (unhashable ``__eq__``-only types, unbounded buffer
views, read-only ``frombuffer`` cells, unbounded recursion at the native
boundary) is mechanically checkable. This package is the repo-specific static
pass that checks them: a small AST-walking framework (:mod:`core`) plus one
module per rule family, wired into tier-1 via ``tests/test_static_analysis.py``
so a new violation fails ``pytest`` immediately.

Rule families (see ``docs/analysis.md`` for bad/good examples):

* **PT100/PT101** lock discipline — writes to lock-guarded shared state
  outside ``with self._lock``; lock-acquisition-order cycles.
* **PT200/PT201** resource lifecycle — stop/close/join-owning types
  constructed without ``with``/``try-finally``; ``__del__``-only cleanup.
* **PT300** exception hygiene — broad handlers in data-plane modules that
  swallow without forwarding or re-raising.
* **PT400** JAX purity — host-side side effects (``np.random``, ``time.*``,
  ``.item()``/``.tolist()``, argument mutation) inside jitted functions.
* **PT500/PT501/PT502/PT503** native-buffer safety — ``np.frombuffer``/
  ``memoryview`` results escaping without a writability check or ``.copy()``;
  zero-copy page views built without a per-page bound check; unbounded
  recursion in the native C++ sources; fused batch-buffer ABI descriptors
  missing their byte-capacity fields or pointing at temporaries.
* **PT600** hashability — ``__eq__`` without ``__hash__``.
* **PT700** telemetry span hygiene — spans/stage timers opened in
  instrumented code must close on all paths (``with`` or try/finally), or
  the trace loses stages and stall attribution under-counts them.
* **PT701** BaseException containment — worker loops must not swallow
  ``BaseException``/``KeyboardInterrupt`` without re-raising, forwarding the
  exception object, or exiting the process: eaten cancellation wedges the
  pool in ways supervision cannot detect.
* **PT702** autotune action discipline — knob actuations in
  ``petastorm_tpu/autotune/`` must sit inside a ``decision_span`` (every
  change leaves an explainable ``autotune.decision`` event) and pass their
  values through ``clamp()`` (no knob write can escape the config's
  explicit bounds).
* **PT703** trace-context propagation — spans on the worker/serve data path
  must inherit the propagated ``TraceContext``: no raw ``record_span``
  calls, no hand-rolled ``trace=``/``span=``/``parent=`` identity kwargs.
  An orphan span drops out of every batch's causal tree
  (docs/observability.md, "Causal tracing").
* **PT704** async-signal-safety — code reachable from a ``signal.signal``
  handler (the flight recorder's crash-footer path,
  ``observability/blackbox.py``) must not acquire locks, log, import, open
  files, or allocate through serializers/``Struct.pack``: the interrupted
  frame may hold the very lock (or be mid-``malloc``), deadlocking or
  corrupting the process the handler is trying to describe
  (``analysis/signal_safety.py``).
* **PT800/PT801** worker-pool protocol discipline — consumer switches over
  results-channel message kinds must cover every kind declared in
  ``workers/protocol.MESSAGE_KINDS`` (or carry an else); protocol
  constants/bytes may only be defined in the canonical
  ``workers/protocol.py``. The static complement of the protocol verifier
  (``petastorm_tpu/analysis/protocol/``, ``docs/protocol.md``).
* **PT900/PT901/PT902** cross-language ABI conformance — every ctypes
  ``Structure`` declaring itself a mirror of a C struct is proven
  field-for-field identical under C layout rules (offsets, sizes, kinds,
  plus the ``pstpu_abi_version`` ↔ ``EXPECTED_ABI`` literal sync); every
  ``argtypes``/``restype`` declaration is checked against the ``extern "C"``
  definition it binds; every exported pointer parameter must travel with a
  capacity bound. The ABI is checked, not trusted (``analysis/abi.py``).
* **PT903/PT904** C++ overflow/bounds discipline — bounds comparisons may
  not be multiplication-form (``n * w <= cap`` wraps for corrupt ``n``;
  division-form or an explicit guard required); ``memcpy``/pointer-advance
  code must be dominated by a check naming the destination's capacity
  (``analysis/cpp_safety.py`` — the PR 6 review-bug classes, mechanized).
* **PT1100–PT1103** shared-plane borrow-checking — views into ring slots,
  blob mappings, and chunk mirrors (``try_read_zero_copy``, ``_map_blob``,
  ``mmap_chunk``, pagescan column views) are *borrows* with a producer-owned
  lifetime. PT1100: a borrow stored into longer-lived state without
  registering with the lifetime registry; PT1101: a function returning a
  borrow without a ``:borrows:`` docstring section; PT1102: a borrow
  crossing a pickle/queue/zmq/ring boundary uncopied; PT1103: a borrow's
  manual release reachable only on some paths (``analysis/lifetime.py``,
  the static half of ``native/lifetime.py``).
* **PT1300–PT1303** whole-program thread races — ONE model over all the
  concurrency domains (``analysis/races.py``): cross-module lock-order
  cycles with call-graph edge propagation (PT1300 — PT101 keeps class-local
  cycles, PT1300 owns everything deeper or wider); reads of lock-guarded
  mutable containers with no lock held, with guarded-by inference that
  follows ``self`` helper calls (PT1301); lock-guarded containers escaping
  via return/yield/store so callers mutate them un-guarded (PT1302);
  blocking calls — unbounded ``Condition.wait``/``Event.wait``, blocking
  ``queue.get/put``, ``join``, ``time.sleep``, elastic lease I/O — made
  while holding a lock (PT1303). The static half of the deterministic
  schedule explorer (``analysis/schedule/``, ``petastorm-tpu-race``).
* **PT1200** elastic shard-map determinism — shard maps must be pure
  functions of ``(seed, epoch, members)``: wall-clock reads, module-global
  RNG draws, RNG constructors without an explicit seed, and iteration over
  raw sets are all rejected inside ``elastic/shardmap.py``. Two hosts that
  derive different maps for the same generation double-read or drop row
  groups with no error anywhere (``analysis/elastic_lints.py``).
* **PT1400** sequence sampling determinism — mixture sampling, bucket
  release and packing decisions (``sequence/``,
  ``weighted_sampling_reader.py``) must be reproducible under a fixed
  seed: wall-clock reads, module-global RNG draws and lexically-unseeded
  RNG constructors are rejected, so a training run's data order stays a
  checkpointable fact (``analysis/sequence_lints.py``).
* **PT1500** fabric socket discipline — every blocking socket primitive in
  ``petastorm_tpu/fabric/`` must carry an explicit per-operation timeout
  (``settimeout`` armed in-function, or the socket arrives alongside a
  ``deadline`` parameter) and — for data-moving ops — run under an
  end-to-end ``protocol.Deadline`` budget, so one stalled peer can never
  wedge a reader thread (``analysis/fabric_lints.py``, ``docs/fabric.md``).

Suppress a single finding with ``# noqa: PT###`` (reason encouraged) on its
line; absorb pre-existing findings with an ``analysis_baseline.json`` (see
:func:`core.load_baseline`). CLI: ``python -m petastorm_tpu.analysis`` or the
``petastorm-tpu-lint`` console script.
"""

from __future__ import annotations

from petastorm_tpu.analysis.abi import AbiConformanceChecker
from petastorm_tpu.analysis.autotune_lints import AutotuneActionChecker
from petastorm_tpu.analysis.buffers import NativeBufferChecker
from petastorm_tpu.analysis.core import (Baseline, Checker, Finding, SourceFile,
                                         collect_sources, load_baseline, run_checkers)
from petastorm_tpu.analysis.cpp_safety import CppSafetyChecker
from petastorm_tpu.analysis.elastic_lints import ElasticDeterminismChecker
from petastorm_tpu.analysis.exceptions import (BaseExceptionContainmentChecker,
                                               ExceptionHygieneChecker)
from petastorm_tpu.analysis.fabric_lints import FabricSocketChecker
from petastorm_tpu.analysis.hashability import HashabilityChecker
from petastorm_tpu.analysis.jax_purity import JaxPurityChecker
from petastorm_tpu.analysis.lifecycle import ResourceLifecycleChecker
from petastorm_tpu.analysis.lifetime import LifetimeChecker
from petastorm_tpu.analysis.locks import LockDisciplineChecker
from petastorm_tpu.analysis.protocol_lints import ProtocolLintChecker
from petastorm_tpu.analysis.races import RaceChecker
from petastorm_tpu.analysis.sequence_lints import SequenceDeterminismChecker
from petastorm_tpu.analysis.serve_lints import ServeActuatorChecker
from petastorm_tpu.analysis.signal_safety import SignalSafetyChecker
from petastorm_tpu.analysis.telemetry import TelemetrySpanChecker
from petastorm_tpu.analysis.trace_lints import TraceContextChecker

#: the full first-party rule set, in rule-id order
ALL_CHECKERS = (
    LockDisciplineChecker,
    ResourceLifecycleChecker,
    ExceptionHygieneChecker,
    JaxPurityChecker,
    NativeBufferChecker,
    HashabilityChecker,
    TelemetrySpanChecker,
    BaseExceptionContainmentChecker,
    SignalSafetyChecker,
    AutotuneActionChecker,
    TraceContextChecker,
    ProtocolLintChecker,
    ServeActuatorChecker,
    AbiConformanceChecker,
    CppSafetyChecker,
    LifetimeChecker,
    ElasticDeterminismChecker,
    RaceChecker,
    SequenceDeterminismChecker,
    FabricSocketChecker,
)

#: every individual rule id the registered checkers can emit — the linter
#: meta-test (tests/test_static_analysis.py) demands a committed fixture
#: pair per id, so registering a toothless rule fails tier-1
ALL_RULE_CODES = tuple(c for cls in ALL_CHECKERS for c in cls.rule_codes())


def run_analysis(paths, baseline=None, select=None, ignore=None,
                 keep_suppressed=False):
    """Run every checker over ``paths`` (files or directories).

    :param baseline: a :class:`core.Baseline` (or None) absorbing known findings
    :param select: iterable of rule-id prefixes (e.g. ``['PT1', 'PT500']``)
        restricting which findings are reported; None = all
    :param ignore: iterable of rule-id prefixes to suppress, applied AFTER
        ``select`` — the staged-rollout knob (``--ignore PT8`` ships a new
        family dark)
    :param keep_suppressed: keep noqa'd/baselined findings, annotated via
        :attr:`core.Finding.status` (the ``--format json`` machine mode)
    :returns: sorted list of :class:`Finding` (only ``status == 'open'`` ones
        unless ``keep_suppressed``)
    """
    sources = collect_sources(paths)
    checkers = [cls() for cls in ALL_CHECKERS]
    findings = run_checkers(checkers, sources, baseline=baseline,
                            keep_suppressed=keep_suppressed)
    if select is not None:
        prefixes = tuple(select)
        findings = [f for f in findings if f.code.startswith(prefixes)]
    if ignore is not None and tuple(ignore):
        prefixes = tuple(ignore)
        findings = [f for f in findings if not f.code.startswith(prefixes)]
    return findings


__all__ = [
    'ALL_CHECKERS', 'ALL_RULE_CODES', 'AbiConformanceChecker',
    'AutotuneActionChecker', 'Baseline',
    'BaseExceptionContainmentChecker', 'Checker', 'CppSafetyChecker',
    'ElasticDeterminismChecker', 'ExceptionHygieneChecker',
    'FabricSocketChecker', 'Finding',
    'HashabilityChecker', 'JaxPurityChecker', 'LifetimeChecker',
    'LockDisciplineChecker',
    'NativeBufferChecker', 'ProtocolLintChecker', 'RaceChecker',
    'ResourceLifecycleChecker', 'SequenceDeterminismChecker',
    'ServeActuatorChecker', 'SignalSafetyChecker',
    'SourceFile', 'TelemetrySpanChecker', 'TraceContextChecker',
    'collect_sources', 'load_baseline', 'run_analysis', 'run_checkers',
]
