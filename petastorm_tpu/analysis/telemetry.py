"""PT700 — telemetry span/timer hygiene.

Every span or stage timer opened in instrumented code must be closed on all
paths: an unclosed span never records its event (the trace silently loses the
stage), and an unclosed timer never accumulates its seconds (the stall
attribution under-counts exactly the stage that crashed or early-returned —
the worst possible skew). The observability API is shaped for this: ``span``
and ``stage`` return context managers, so ``with obs.stage('decode'): ...`` is
both the cheapest and the only lint-clean form.

A span-opening call is flagged unless one of these holds:

* it is the context expression of a ``with`` (the canonical form);
* it is assigned to a name that is later entered with ``with`` or explicitly
  closed (``.end()``/``.finish()``/``.close()``/``.stop()``/``.__exit__()``)
  inside a ``finally`` block of the same function;
* ownership escapes — the result is returned/yielded or passed to another
  call.

Matched openers: bare ``span(...)``/``stage(...)`` calls, the same names on
an observability-module receiver (``obs.stage(...)``,
``observability.span(...)``, ``trace.span(...)``), and the unambiguous
``start_span``/``begin_span``/``start_timer`` spellings on any receiver.
``m.span()`` on a regex match (or any other non-telemetry receiver) is not
matched.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, add_parents, walk_functions

#: names only matched as bare calls or on a telemetry-module receiver
_AMBIGUOUS_OPENERS = {'span', 'stage'}

#: names matched on any receiver (no non-telemetry meaning in this tree)
_UNAMBIGUOUS_OPENERS = {'start_span', 'begin_span', 'start_timer', 'begin_timer'}

#: module-style receivers that mark span/stage as telemetry calls
_TELEMETRY_RECEIVERS = {'obs', 'observability', 'telemetry', 'trace', 'tracing'}

_CLOSERS = {'end', 'finish', 'close', 'stop', '__exit__'}


def _opener_name(call):
    """The opener name when ``call`` opens a span/timer, else None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in _AMBIGUOUS_OPENERS or func.id in _UNAMBIGUOUS_OPENERS:
            return func.id
        return None
    if isinstance(func, ast.Attribute):
        if func.attr in _UNAMBIGUOUS_OPENERS:
            return func.attr
        if func.attr in _AMBIGUOUS_OPENERS and isinstance(func.value, ast.Name) \
                and func.value.id in _TELEMETRY_RECEIVERS:
            return func.attr
    return None


def _closed_or_reentered(func, name):
    """Is the name (bound to an opened span) entered with ``with`` anywhere,
    or closed inside a ``finally`` block, within ``func``?"""
    for node in ast.walk(func):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id == name:
                    return True
        elif isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in _CLOSERS \
                            and isinstance(sub.func.value, ast.Name) \
                            and sub.func.value.id == name:
                        return True
    return False


class TelemetrySpanChecker(Checker):
    code = 'PT700'
    name = 'telemetry-span-hygiene'
    description = ('span/stage timers opened without a with-block or a '
                   'try/finally close: a leaked span skews stall attribution')
    scope = ('*.py',)

    def check(self, src):
        add_parents(src.tree)
        for func, _cls in walk_functions(src.tree):
            yield from self._check_function(src, func)

    def _check_function(self, src, func):
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            opener = _opener_name(node)
            if opener is None:
                continue
            parent = getattr(node, 'pt_parent', None)
            # `with span(...)`: canonical
            if isinstance(parent, ast.withitem):
                continue
            # ownership escapes: returned/yielded, passed to another call,
            # stored into an attribute/container (an owner manages it)
            if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                                   ast.Call, ast.Starred, ast.keyword)):
                continue
            if isinstance(parent, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in parent.targets):
                    continue
                names = [t.id for t in parent.targets if isinstance(t, ast.Name)]
                if names and all(_closed_or_reentered(func, n) for n in names):
                    continue
                yield self.finding(
                    src, node.lineno,
                    "span/timer from {}(...) bound to {} but not closed on all "
                    "paths in {}(): use 'with', or close it in a try/finally".format(
                        opener, ' / '.join(repr(n) for n in names) or 'a target',
                        func.name))
                continue
            # bare expression (opened and dropped) or any other use: the span
            # can never be closed
            yield self.finding(
                src, node.lineno,
                '{}(...) opened without entering its context in {}() — the '
                'span/timer never closes and its stage is lost from '
                'attribution'.format(opener, func.name))
