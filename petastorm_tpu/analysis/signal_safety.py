"""PT704 — signal-handler-reachable code must be async-signal-safe.

The flight recorder (``observability/blackbox.py``) stamps its crash-cause
footer from inside a Python signal handler: that code runs at an arbitrary
bytecode boundary of whatever the main thread was doing.  The rules there
are stricter than ordinary thread safety:

* **no lock acquisition** — if the interrupted code holds the lock, the
  handler deadlocks the process it was trying to forensically describe;
* **no logging** — the logging module takes a module-level lock and
  allocates handlers/records (same deadlock, plus reentrancy);
* **no imports** — the import system takes the import lock and runs
  arbitrary module code;
* **no allocation-heavy calls** — ``open()``, ``json``/``pickle``
  serialization and ``Struct.pack`` all allocate; an allocation while the
  interrupted frame is mid-``malloc`` corrupts the heap in the worst case
  and raises ``MemoryError`` inside the handler in the best.
  ``Struct.pack_into`` on a preallocated buffer is the sanctioned pattern.

The checker discovers handler entry points lexically — functions installed
with ``signal.signal(sig, fn)`` — then walks the intra-module call graph
(plain calls by name, method calls by attribute name) and reports the
violations reachable from any handler.  Code that is NOT handler-reachable
may freely lock and log; only the handler cone is constrained.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, attr_chain, walk_functions

#: dotted-call chains that allocate (or serialize, which allocates)
_ALLOCATING_CALLS = {'json.dumps', 'json.loads', 'json.dump', 'json.load',
                     'pickle.dumps', 'pickle.loads', 'pickle.dump',
                     'pickle.load', 'marshal.dumps', 'marshal.loads'}

#: call bases whose methods route through the logging module
_LOGGING_BASES = ('logger', 'logging', 'log')


def _call_name(call):
    """Dotted chain of a call's target ('signal.signal', 'self._lock.acquire',
    'open'), or None for computed targets."""
    if isinstance(call.func, ast.Name):
        return call.func.id
    return attr_chain(call.func)


def _tail(chain):
    return chain.rsplit('.', 1)[-1]


def _handler_roots(tree):
    """Function names installed as signal handlers anywhere in the module:
    the second argument of ``signal.signal(sig, fn)`` when it names a local
    function or method (``SIG_DFL``/``SIG_IGN`` and foreign callables are
    not entry points we can check)."""
    roots = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _call_name(node)
        if chain is None or _tail(chain) != 'signal' or len(node.args) < 2:
            continue
        if not (chain == 'signal' or chain.endswith('.signal')):
            continue
        handler = node.args[1]
        name = None
        if isinstance(handler, ast.Name):
            name = handler.id
        elif isinstance(handler, ast.Attribute):
            name = handler.attr
        if name and name not in ('SIG_DFL', 'SIG_IGN'):
            roots.add(name)
    return roots


class SignalSafetyChecker(Checker):
    code = 'PT704'
    name = 'async-signal-safety'
    description = ('code reachable from a signal handler must not acquire '
                   'locks, log, import, open files, or allocate through '
                   'serializers/Struct.pack — the interrupted frame may hold '
                   'the very lock (or be mid-malloc), deadlocking or '
                   'corrupting the process the handler is trying to describe')
    scope = ('*observability/blackbox*.py',)

    def check(self, src):
        funcs = {}
        for func, _cls in walk_functions(src.tree):
            funcs.setdefault(func.name, []).append(func)
        roots = _handler_roots(src.tree) & set(funcs)
        if not roots:
            return
        # BFS over the intra-module call graph: plain calls by name, method
        # calls by attribute name (receiver types are not resolved — a
        # same-named local function is conservatively treated as reachable)
        reachable, frontier = set(roots), list(roots)
        while frontier:
            name = frontier.pop()
            for func in funcs[name]:
                for node in ast.walk(func):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = _call_name(node)
                    if chain is None:
                        continue
                    callee = _tail(chain)
                    if callee in funcs and callee not in reachable:
                        reachable.add(callee)
                        frontier.append(callee)
        for name in sorted(reachable):
            for func in funcs[name]:
                yield from self._check_function(src, func)

    def _check_function(self, src, func):
        where = 'handler-reachable `{}`'.format(func.name)
        for node in ast.walk(func):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                yield self.finding(
                    src, node.lineno,
                    'import inside {}: the import system takes the import '
                    'lock and runs module code — hoist to module scope'.format(where))
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    chain = attr_chain(expr) or ''
                    if 'lock' in chain.lower():
                        yield self.finding(
                            src, node.lineno,
                            '`with {}` inside {}: the interrupted frame may '
                            'already hold it — a signal handler that blocks '
                            'on a lock deadlocks the process'.format(chain, where))
            elif isinstance(node, ast.Call):
                yield from self._check_call(src, node, where)

    def _check_call(self, src, call, where):
        chain = _call_name(call)
        if chain is None:
            return
        tail = _tail(chain)
        if tail == 'acquire' and 'lock' in chain.lower():
            yield self.finding(
                src, call.lineno,
                '{}() inside {}: a signal handler must never block on a '
                'lock the interrupted frame may hold'.format(chain, where))
        elif chain.split('.', 1)[0] in _LOGGING_BASES and tail in (
                'debug', 'info', 'warning', 'error', 'exception', 'critical', 'log'):
            yield self.finding(
                src, call.lineno,
                '{}() inside {}: logging locks and allocates — stamp a '
                'preallocated buffer instead'.format(chain, where))
        elif chain == 'open':
            yield self.finding(
                src, call.lineno,
                'open() inside {}: allocates and may block — keep the fd '
                'open for the process lifetime instead'.format(where))
        elif chain in _ALLOCATING_CALLS:
            yield self.finding(
                src, call.lineno,
                '{}() inside {}: serialization allocates — the handler may '
                'interrupt a frame mid-malloc'.format(chain, where))
        elif tail == 'pack' and '.' in chain:
            yield self.finding(
                src, call.lineno,
                '{}() inside {}: Struct.pack allocates a fresh bytes object '
                'per call — use pack_into on a preallocated buffer'.format(
                    chain, where))
