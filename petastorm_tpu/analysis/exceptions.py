"""PT300 — exception hygiene in the data plane.

A worker/transport/decoder exception that is silently swallowed does not
vanish: it resurfaces as a hung consumer (an item counted ventilated but never
completed), a short epoch, or corrupt state. The pools therefore have an
explicit error channel — thread pool workers forward through the results
queue, process workers pickle the exception over the transport — and every
broad handler in the data plane must either re-raise, forward, log, or carry a
reviewed justification.

Flagged: a bare ``except:`` or ``except Exception/BaseException`` handler that
*swallows* — no ``raise``, the bound exception (if any) is never referenced,
and the body performs no call at all (a call is evidence of handling:
forwarding to the error channel, logging, cleanup, a fallback path). The
existing ``# noqa: BLE001 - reason`` annotations are honored as suppressions
(alias of PT300), so the tree's pre-reviewed handlers stay quiet.

Scope: the data-plane modules — workers, reader/worker/serializer stack,
native bindings, jax loader/infeed — not the ETL/CLI long tail, where a
swallow costs a warning, not a training run.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker

_BROAD = {'Exception', 'BaseException'}


def _is_broad(handler):
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in _BROAD for el in t.elts)
    return False


def _body_swallows(handler):
    """True when the handler neither raises, nor references the bound
    exception, nor calls anything."""
    bound = handler.name
    for node in ast.walk(handler):
        if node is handler.type:
            continue
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            return False
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return False
    return True


class ExceptionHygieneChecker(Checker):
    code = 'PT300'
    name = 'exception-hygiene'
    description = ('broad except that swallows without forwarding to the error '
                   'channel, logging, or re-raising (data-plane modules)')
    scope = ('*workers/*.py', '*native/*.py', '*jax/*.py',
             '*reader.py', '*row_worker.py', '*batch_worker.py', '*serializers.py',
             '*shuffling_buffer.py', '*columnar.py', '*rebatch.py',
             '*cache.py', '*local_disk_cache.py', '*retry.py',
             '*chunkstore/*.py', '*fabric/*.py')

    def check(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _body_swallows(node):
                what = ('bare except' if node.type is None else
                        'except {}'.format(ast.unparse(node.type)))
                yield self.finding(
                    src, node.lineno,
                    '{} swallows silently — forward to the pool error channel, '
                    'log, re-raise, or annotate why discarding is safe'.format(what))


# ---------------------------------------------------------------------------
# PT701 — BaseException containment in worker loops
# ---------------------------------------------------------------------------

_UNCATCHABLE = {'BaseException', 'KeyboardInterrupt', 'SystemExit', 'GeneratorExit'}


def _catches_uncatchable(handler):
    """Names from :data:`_UNCATCHABLE` this handler's type clause catches
    EXPLICITLY (a bare ``except:`` is PT300's concern)."""
    t = handler.type
    names = []
    if isinstance(t, ast.Name) and t.id in _UNCATCHABLE:
        names.append(t.id)
    elif isinstance(t, ast.Tuple):
        names.extend(el.id for el in t.elts
                     if isinstance(el, ast.Name) and el.id in _UNCATCHABLE)
    return names


def _contains_or_forwards(handler):
    """True when the handler re-raises (any ``raise``), forwards the bound
    exception (references its name — e.g. handing it to the pool's error
    channel for the consumer to re-raise), or terminates the process
    (``os._exit``/``sys.exit`` — a worker's deliberate suicide)."""
    bound = handler.name
    for node in ast.walk(handler):
        if node is handler.type:
            continue
        if isinstance(node, ast.Raise):
            return True
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ('_exit', 'exit') \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in ('os', 'sys'):
            return True
    return False


class BaseExceptionContainmentChecker(Checker):
    """PT701 — worker/consumer loops must not swallow ``BaseException`` /
    ``KeyboardInterrupt``.

    The supervision layer (docs/robustness.md) is built on failures
    PROPAGATING: a worker loop that catches ``BaseException`` and carries on
    converts Ctrl-C into a hung pool (the consumer waits forever for a result
    the interrupted worker will never send) and converts ``SystemExit`` into a
    zombie worker the supervisor cannot distinguish from a healthy one.
    Catching these is only legitimate to clean up and re-raise, to forward the
    exception object to the error channel, or to deliberately kill the
    process — anything else is flagged. Stricter than PT300: logging alone
    does NOT absolve a ``BaseException`` handler."""

    code = 'PT701'
    name = 'baseexception-containment'
    description = ('except BaseException/KeyboardInterrupt that neither re-raises, '
                   'forwards the exception, nor exits the process (worker loops '
                   'must let cancellation through)')
    scope = ExceptionHygieneChecker.scope

    def check(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            caught = _catches_uncatchable(node)
            if caught and not _contains_or_forwards(node):
                yield self.finding(
                    src, node.lineno,
                    'except {} swallowed without re-raising — a worker loop that '
                    'eats cancellation/interpreter-shutdown wedges the pool; '
                    're-raise, forward the exception object, or os._exit'.format(
                        '/'.join(caught)))
