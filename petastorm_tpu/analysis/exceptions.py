"""PT300 — exception hygiene in the data plane.

A worker/transport/decoder exception that is silently swallowed does not
vanish: it resurfaces as a hung consumer (an item counted ventilated but never
completed), a short epoch, or corrupt state. The pools therefore have an
explicit error channel — thread pool workers forward through the results
queue, process workers pickle the exception over the transport — and every
broad handler in the data plane must either re-raise, forward, log, or carry a
reviewed justification.

Flagged: a bare ``except:`` or ``except Exception/BaseException`` handler that
*swallows* — no ``raise``, the bound exception (if any) is never referenced,
and the body performs no call at all (a call is evidence of handling:
forwarding to the error channel, logging, cleanup, a fallback path). The
existing ``# noqa: BLE001 - reason`` annotations are honored as suppressions
(alias of PT300), so the tree's pre-reviewed handlers stay quiet.

Scope: the data-plane modules — workers, reader/worker/serializer stack,
native bindings, jax loader/infeed — not the ETL/CLI long tail, where a
swallow costs a warning, not a training run.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker

_BROAD = {'Exception', 'BaseException'}


def _is_broad(handler):
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in _BROAD for el in t.elts)
    return False


def _body_swallows(handler):
    """True when the handler neither raises, nor references the bound
    exception, nor calls anything."""
    bound = handler.name
    for node in ast.walk(handler):
        if node is handler.type:
            continue
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            return False
        if bound and isinstance(node, ast.Name) and node.id == bound:
            return False
    return True


class ExceptionHygieneChecker(Checker):
    code = 'PT300'
    name = 'exception-hygiene'
    description = ('broad except that swallows without forwarding to the error '
                   'channel, logging, or re-raising (data-plane modules)')
    scope = ('*workers/*.py', '*native/*.py', '*jax/*.py',
             '*reader.py', '*row_worker.py', '*batch_worker.py', '*serializers.py',
             '*shuffling_buffer.py', '*columnar.py', '*rebatch.py',
             '*cache.py', '*local_disk_cache.py', '*retry.py',
             '*chunkstore/*.py')

    def check(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and _body_swallows(node):
                what = ('bare except' if node.type is None else
                        'except {}'.format(ast.unparse(node.type)))
                yield self.finding(
                    src, node.lineno,
                    '{} swallows silently — forward to the pool error channel, '
                    'log, re-raise, or annotate why discarding is safe'.format(what))
