"""PT200/PT201 — resource lifecycle.

**PT200** Types exposing ``stop``/``join``/``close``/``shutdown`` (the
Reader, the pools, the shm ring, pagescan mmaps) own OS resources — threads,
spawned processes, shared-memory segments, file descriptors. Constructing one
at a call site and letting it fall out of scope leaves cleanup to the GC (or
to nothing at all: daemon threads and /dev/shm segments survive their Python
wrapper). A construction is *orphaned* when the result is not entered with
``with``, closed in the enclosing function, assigned to an attribute/
container, returned/yielded, or handed to another call that takes ownership.

**PT201** Cleanup reachable only through ``__del__`` is cleanup scheduled by
the GC: under CPython reference cycles or interpreter teardown it runs late,
never, or against half-torn module globals. A class defining ``__del__``
must also expose a deterministic release path (``close``/``stop``/``join``/
``shutdown``/``__exit__``), with ``__del__`` as the last-resort backstop only.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, add_parents, walk_functions

_RELEASE_METHODS = {'close', 'stop', 'join', 'shutdown', 'release', 'terminate',
                    '__exit__'}

#: resource types outside the scanned file set that call sites still construct
_KNOWN_RESOURCE_CLASSES = {'Reader', 'ThreadPool', 'ProcessPool', 'DummyPool',
                           'ShmRing', 'NativeParquetFile', 'JaxDataLoader'}


def _collect_resource_classes(src):
    """Class names in this module whose instances need explicit release:
    they define a release method (or __enter__/__exit__). Purely-protocol
    bases (all release methods empty/abstract) still count — the point is the
    call-site contract, not the body."""
    classes = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        defined = {n.name for n in node.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if defined & _RELEASE_METHODS:
            classes.add(node.name)
    return classes


def _constructed_class(call, resource_classes):
    """Class name when ``call`` constructs a resource: ``Cls(...)`` or the
    ``Cls.create(...)``/``Cls.attach(...)``/``Cls.open(...)`` factory idiom."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in resource_classes:
        return func.id
    if isinstance(func, ast.Attribute) and func.attr in ('create', 'attach', 'open') \
            and isinstance(func.value, ast.Name) and func.value.id in resource_classes:
        return func.value.id
    return None


def _enclosing_function(node):
    cur = getattr(node, 'pt_parent', None)
    while cur is not None and not isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        cur = getattr(cur, 'pt_parent', None)
    return cur


def _under_with_or_try(node, stop_at):
    """True when ``node`` sits inside a ``with`` item, a ``with`` body, or a
    ``try`` that has a ``finally`` — before reaching ``stop_at``."""
    cur = node
    while cur is not None and cur is not stop_at:
        parent = getattr(cur, 'pt_parent', None)
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            return True
        if isinstance(parent, ast.Try) and parent.finalbody:
            return True
        cur = parent
    return False


def _name_released_or_escapes(func, name):
    """Within ``func``: does ``name`` get released, escape, or change owner?
    Escapes: returned/yielded, stored into an attribute/container, passed as a
    call argument, or re-raised into a with/try-finally via ``with name``."""
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                    and f.value.id == name and f.attr in _RELEASE_METHODS:
                return True
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
                if isinstance(arg, ast.Starred) and isinstance(arg.value, ast.Name) \
                        and arg.value.id == name:
                    return True
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
        elif isinstance(node, ast.Assign):
            uses_name = any(isinstance(s, ast.Name) and s.id == name
                            for s in ast.walk(node.value))
            stores_out = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in node.targets)
            if uses_name and stores_out:
                return True
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
    return False


class ResourceLifecycleChecker(Checker):
    code = 'PT200'
    codes = ('PT200', 'PT201')
    name = 'resource-lifecycle'
    description = ('resource types constructed without with/try-finally or a '
                   'release path; __del__-only cleanup (PT201)')
    scope = ('*.py',)

    def check(self, src):
        add_parents(src.tree)
        resource_classes = _collect_resource_classes(src) | _KNOWN_RESOURCE_CLASSES
        yield from self._check_del_only(src)
        yield from self._check_orphans(src, resource_classes)

    def _check_del_only(self, src):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            defined = {n.name for n in node.body
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
            if '__del__' in defined and not (defined & _RELEASE_METHODS):
                yield self.finding(
                    src, node.lineno,
                    "class {} cleans up only in __del__ — add a deterministic "
                    'close()/stop() (GC may run it late, never, or at interpreter '
                    'teardown)'.format(node.name),
                    code='PT201')

    def _check_orphans(self, src, resource_classes):
        for func, cls in walk_functions(src.tree):
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                cls_name = _constructed_class(node, resource_classes)
                if cls_name is None:
                    continue
                if _enclosing_function(node) is not func:
                    continue  # belongs to a nested def: reported for that def
                parent = getattr(node, 'pt_parent', None)
                # `with Cls(...)` / `return Cls(...)` / `yield Cls(...)` /
                # `f(Cls(...))` / `x.append(Cls(...))` / self.attr = Cls(...):
                # ownership moves or release is structural
                if isinstance(parent, (ast.withitem, ast.Return, ast.Yield,
                                       ast.YieldFrom, ast.Call, ast.Starred)):
                    continue
                if isinstance(parent, ast.Assign):
                    targets = parent.targets
                    if any(isinstance(t, (ast.Attribute, ast.Subscript)) for t in targets):
                        continue  # owner object/container manages it
                    names = [t.id for t in targets if isinstance(t, ast.Name)]
                    if names and all(_name_released_or_escapes(func, n) for n in names):
                        continue
                    if _under_with_or_try(node, func):
                        continue
                    yield self.finding(
                        src, node.lineno,
                        '{} constructed but never released in {}(): call .close()/'
                        '.stop()+.join(), use "with", or hand it to an owner'.format(
                            cls_name, func.name))
                elif isinstance(parent, ast.Expr):
                    # bare `Cls(...)` statement: constructed and dropped
                    yield self.finding(
                        src, node.lineno,
                        '{} constructed and immediately discarded in {}() — its '
                        'threads/processes/fds leak until GC'.format(cls_name, func.name))
