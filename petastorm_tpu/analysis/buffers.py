"""PT500/PT501/PT502 — safety of zero-copy views at the native boundary.

**PT500** ``np.frombuffer``/``memoryview`` results are views over transport
or file memory: over a zmq ``bytes`` they are read-only (an in-place image op
or ``torch.from_numpy`` then fails — or worse, behaves transport-dependently),
over a shared ring they alias memory with its own lifetime. A view that
*escapes* a function (returned, yielded, or stored into a container cell)
must either be ``.copy()``-ed or the function must gate on writability
(``.flags.writeable`` / ``memoryview.readonly``) — otherwise downstream
behavior depends on which transport the payload happened to ride (the
round-5 serializer defect class).

**PT501** A zero-copy Arrow view over an mmap'd Parquet page
(``pa.py_buffer(memoryview(mm)[off:off + n])``) trusts ``n`` — which derives
from footer metadata a third-party writer produced. Bounds-checking ``n``
against the *whole file* only means a wrong ``null_count``/short page silently
serves the next page's header bytes as tensor data. The function building such
views must compare the view length against a per-page bound (any comparison of
the length name with something other than the mmap's ``.size``) — the round-5
pagescan defect class.

**PT502** (C++ sources) Parsers at the native boundary consume untrusted
bytes; a recursive descent with no depth bound turns a corrupt/crafted
deeply-nested input into C++ stack exhaustion — a process crash no Python
``except`` can catch (the round-5 thrift ``skip_value`` defect class). Every
function participating in a recursion cycle in ``native/*.cpp`` must mention
a ``depth`` limit.

**PT503** The fused batch-buffer ABI (``native/fused.py`` ↔
``pstpu_read_fused``) carries raw pointers with explicit byte capacities.
Two invariants keep it memory-safe from the Python side:

* *lifetime anchored* — a raw address (``X.ctypes.data``) taken from a
  TEMPORARY expression (``np.empty(n).ctypes.data``) dies before or at the
  foreign call; the owning buffer must be bound to a name that outlives the
  call;
* *bounds arguments present* — a function that stores a descriptor pointer
  field (``.out`` / ``.chunk`` / ``.aux_buf``) must store its matching
  capacity field (``.out_cap`` / ``.chunk_len`` / ``.aux_cap``) in the same
  function, so the kernel always receives the bound it checks against.
"""

from __future__ import annotations

import ast
import re

from petastorm_tpu.analysis.core import Checker, add_parents, attr_chain, walk_functions

_VIEW_CALLS = {'frombuffer', 'memoryview'}
_GUARD_TOKENS = ('writeable', 'readonly')

#: methods whose result is still (possibly) a read-only view over the same
#: memory; anything else (.sum(), .astype(), .tolist(), ...) derives fresh data
_VIEW_METHODS = {'reshape', 'cast', 'view', 'transpose', 'swapaxes', 'squeeze',
                 'ravel'}


def _is_view_call(node):
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id in _VIEW_CALLS
    if isinstance(func, ast.Attribute):
        return func.attr in _VIEW_CALLS
    return False


def _chained_copy(node):
    """True when the view is immediately copied: np.frombuffer(...).copy()
    possibly through reshape/cast links."""
    cur = getattr(node, 'pt_parent', None)
    while isinstance(cur, (ast.Attribute, ast.Call)):
        if isinstance(cur, ast.Attribute) and cur.attr in ('copy', 'tobytes'):
            return True
        cur = getattr(cur, 'pt_parent', None)
    return False


def _function_has_guard(fn, src):
    """A writability gate anywhere in the function counts: the function is
    the review unit, and a guard like ``v if v.flags.writeable else v.copy()``
    covers sibling view expressions."""
    seg = ast.get_source_segment(src.text, fn) or ''
    return any(tok in seg for tok in _GUARD_TOKENS)


def _escape_kind(node, fn):
    """'returned' / 'stored' when the view expression escapes ``fn``."""
    view_names = set()
    cur, parent = node, getattr(node, 'pt_parent', None)
    # walk through wrapper chains (reshape/cast/slicing keep it a view); stop
    # when the view becomes an ARGUMENT of another call or the receiver of a
    # data-deriving method (consumed, not escaping)
    while True:
        if isinstance(parent, ast.Attribute):
            cur, parent = parent, getattr(parent, 'pt_parent', None)
        elif isinstance(parent, ast.Subscript) and parent.value is cur:
            cur, parent = parent, getattr(parent, 'pt_parent', None)
        elif isinstance(parent, ast.Call) and parent.func is cur:
            if isinstance(cur, ast.Attribute) and cur.attr not in _VIEW_METHODS:
                return None  # .sum()/.astype()/...: result is fresh data
            cur, parent = parent, getattr(parent, 'pt_parent', None)
        else:
            break
    if isinstance(parent, (ast.Return, ast.Yield)):
        return 'returned'
    if isinstance(parent, ast.Assign):
        if any(isinstance(t, ast.Subscript) for t in parent.targets):
            return 'stored'
        view_names = {t.id for t in parent.targets if isinstance(t, ast.Name)}
    if not view_names:
        return None
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.Return, ast.Yield)) and sub.value is not None:
            if _name_escapes_expr(sub.value, view_names):
                return 'returned'
        elif isinstance(sub, ast.Assign):
            stores_out = any(isinstance(t, ast.Subscript) for t in sub.targets)
            if stores_out and _name_escapes_expr(sub.value, view_names):
                return 'stored'
    return None


def _name_escapes_expr(expr, view_names):
    """A view name escapes through ``expr`` only when it is NOT consumed as an
    argument of some call on the way up (``pickle.loads(mv[1:])`` consumes the
    view; ``mv[1:]`` re-exports it)."""
    for n in ast.walk(expr):
        if not (isinstance(n, ast.Name) and n.id in view_names):
            continue
        cur = n
        consumed = False
        while cur is not expr and not consumed:
            parent = getattr(cur, 'pt_parent', None)
            if parent is None:
                break
            if isinstance(parent, ast.Call):
                if cur is not parent.func:
                    consumed = True  # argument of some call
                elif isinstance(cur, ast.Attribute) and cur.attr not in _VIEW_METHODS:
                    consumed = True  # .sum()/.astype()/...: fresh data
            cur = parent
        if not consumed:
            return True
    return False


class NativeBufferChecker(Checker):
    code = 'PT500'
    codes = ('PT500', 'PT501', 'PT502', 'PT503')
    name = 'native-buffer-safety'
    description = ('frombuffer/memoryview escaping without copy or writability '
                   'check; unbounded page views (PT501); unbounded native '
                   'recursion (PT502)')
    scope = ('*serializers.py', '*native/*.py', '*native/*.cpp', '*native/*.cc')

    def check(self, src):
        if src.is_python:
            add_parents(src.tree)
            yield from self._check_views(src)
            yield from self._check_page_bounds(src)
            yield from self._check_fused_abi(src)
        else:
            yield from self._check_cpp_recursion(src)

    # -- PT500 ---------------------------------------------------------------

    def _check_views(self, src):
        for fn, _cls in walk_functions(src.tree):
            has_guard = _function_has_guard(fn, src)
            for node in ast.walk(fn):
                if not _is_view_call(node) or _chained_copy(node):
                    continue
                kind = _escape_kind(node, fn)
                if kind is None or has_guard:
                    continue
                yield self.finding(
                    src, node.lineno,
                    'buffer view {} from {}() without .copy() or a writability '
                    'check — writability (and lifetime) depends on the transport '
                    'the bytes rode'.format(kind, fn.name))

    # -- PT501 ---------------------------------------------------------------

    def _check_page_bounds(self, src):
        for fn, _cls in walk_functions(src.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func) or ''
                if chain.rsplit('.', 1)[-1] != 'py_buffer' or not node.args:
                    continue
                length_names = self._slice_length_names(node.args[0])
                if not length_names:
                    continue
                if not self._has_page_bound_compare(fn, length_names):
                    yield self.finding(
                        src, node.lineno,
                        'zero-copy page view built in {}() with no per-page bound '
                        'check on {} — a wrong null_count/short page serves '
                        "the next page's bytes as tensor data".format(
                            fn.name, ' / '.join(sorted(length_names))),
                        code='PT501')

    @staticmethod
    def _slice_length_names(arg):
        """Names participating in the slice bounds of ``memoryview(mm)[a:b]``
        (and plain ``mm[a:b]``) — the values a bound check must constrain."""
        names = set()
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Subscript) and isinstance(sub.slice, ast.Slice):
                for bound in (sub.slice.lower, sub.slice.upper):
                    if bound is None:
                        continue
                    for n in ast.walk(bound):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        return names

    @staticmethod
    def _has_page_bound_compare(fn, length_names):
        """A comparison involving a slice-length name where the other side is
        NOT a whole-file ``.size``/``len()`` — i.e. an actual per-page bound."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            operands = [node.left] + list(node.comparators)
            involves = any(isinstance(n, ast.Name) and n.id in length_names
                           for op in operands for n in ast.walk(op))
            if not involves:
                continue
            for op in operands:
                chain = attr_chain(op)
                if chain is not None and chain.endswith('.size'):
                    continue  # whole-file bound: not sufficient
                if any(isinstance(n, ast.Name) and n.id in length_names
                       for n in ast.walk(op)):
                    continue  # the length side itself
                return True
        return False

    # -- PT503 ---------------------------------------------------------------

    #: descriptor pointer field -> the capacity field the kernel bounds it by
    _PTR_BOUND_FIELDS = {'out': 'out_cap', 'chunk': 'chunk_len',
                         'aux_buf': 'aux_cap'}

    def _check_fused_abi(self, src):
        for fn, _cls in walk_functions(src.tree):
            assigned = set()
            for node in ast.walk(fn):
                # lifetime: <temporary>.ctypes.data — the array dies at the
                # end of the expression, before the kernel dereferences it
                if isinstance(node, ast.Attribute) and node.attr in ('data', 'data_as'):
                    inner = node.value
                    if isinstance(inner, ast.Attribute) and inner.attr == 'ctypes' \
                            and isinstance(inner.value, ast.Call):
                        yield self.finding(
                            src, node.lineno,
                            'raw pointer taken from a temporary expression in {}() '
                            '— bind the buffer to a name that outlives the native '
                            'call (the temporary is freed before the kernel '
                            'dereferences it)'.format(fn.name),
                            code='PT503')
                if isinstance(node, ast.Assign):
                    assigned.update(t.attr for t in node.targets
                                    if isinstance(t, ast.Attribute))
            for ptr, bound in self._PTR_BOUND_FIELDS.items():
                if ptr in assigned and bound not in assigned:
                    yield self.finding(
                        src, fn.lineno,
                        'fused-ABI descriptor pointer .{} is set in {}() without '
                        'its capacity field .{} — the kernel bounds every write '
                        'by that capacity, so a descriptor without it is an '
                        'unbounded native write'.format(ptr, fn.name, bound),
                        code='PT503')

    # -- PT502 ---------------------------------------------------------------

    #: a (loose) C++ function definition: identifier immediately before '(',
    #: with the body brace on the same or a following line
    _CPP_DEF_RE = re.compile(
        r'^[ \t]*(?:[A-Za-z_][\w:<>,*&\s]*?[\s*&])?'
        r'(?:[A-Za-z_][\w]*::)?(?P<name>~?[A-Za-z_]\w*)\s*\([^;{}]*\)'
        r'(?:\s*const)?(?:\s*noexcept)?\s*\{', re.MULTILINE)

    _CPP_KEYWORDS = {'if', 'for', 'while', 'switch', 'return', 'catch', 'sizeof',
                     'defined'}

    def _check_cpp_recursion(self, src):
        text = _strip_cpp_comments_and_strings(src.text)
        bodies = {}   # name -> (lineno, body text incl. signature)
        for m in self._CPP_DEF_RE.finditer(text):
            name = m.group('name')
            if name in self._CPP_KEYWORDS:
                continue
            open_brace = text.index('{', m.end() - 1)
            end = _match_brace(text, open_brace)
            if end is None:
                continue
            lineno = text.count('\n', 0, m.start()) + 1
            # keep the first definition; overloads share the identifier and
            # the depth requirement applies to the cycle either way
            bodies.setdefault(name, (lineno, text[m.start():end + 1]))
        calls = {}
        for name, (_lineno, body) in bodies.items():
            inner = body[body.index('{'):]  # calls in the BODY, not the signature
            calls[name] = {callee for callee in bodies
                           if re.search(r'\b{}\s*\('.format(re.escape(callee)), inner)}
        for name in sorted(bodies):
            if not _in_cycle(name, calls):
                continue
            lineno, body = bodies[name]
            if re.search(r'\bdepth\b', body, re.IGNORECASE):
                continue
            yield self.finding(
                src, lineno,
                'recursive native function {}() has no depth bound — corrupt '
                'deeply-nested input overflows the C++ stack and kills the '
                'process (no Python except can catch it)'.format(name),
                code='PT502')


def _strip_cpp_comments_and_strings(text):
    """Blank out // and /* */ comments and string/char literals, preserving
    line structure so reported linenos stay correct."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '/' and i + 1 < n and text[i + 1] == '/':
            j = text.find('\n', i)
            j = n if j == -1 else j
            out.append(' ' * (j - i))
            i = j
        elif c == '/' and i + 1 < n and text[i + 1] == '*':
            j = text.find('*/', i + 2)
            j = n if j == -1 else j + 2
            out.append(''.join('\n' if ch == '\n' else ' ' for ch in text[i:j]))
            i = j
        elif c in '"\'':
            j = i + 1
            while j < n and text[j] != c:
                j += 2 if text[j] == '\\' else 1
            j = min(j + 1, n)
            out.append(c + ' ' * (j - i - 2 if j - i >= 2 else 0) + (c if j - i >= 2 else ''))
            i = j
        else:
            out.append(c)
            i += 1
    return ''.join(out)


def _match_brace(text, open_idx):
    depth = 0
    for i in range(open_idx, len(text)):
        if text[i] == '{':
            depth += 1
        elif text[i] == '}':
            depth -= 1
            if depth == 0:
                return i
    return None


def _in_cycle(start, calls):
    """Is ``start`` on a call cycle (including self-recursion)?"""
    stack = [c for c in calls.get(start, ())]
    seen = set()
    while stack:
        cur = stack.pop()
        if cur == start:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(calls.get(cur, ()))
    return False
