"""PT903/PT904 — overflow- and bounds-discipline lints for the C++ kernels.

Both memory-safety bugs the PR 6 review caught in ``rowgroup_reader.cpp``
were instances of two checkable shapes, encoded here so the next instance is
a lint failure instead of a review catch:

**PT903 — multiplication-form bounds comparison.** ``n * width <= cap``
wraps: a corrupt chunk declaring ``n ~ 2**61`` values makes the product
overflow ``uint64`` to a tiny number, sneaks past the check, and the decode
loop reads/writes far out of bounds (the shipped dictionary-page bug).
Every comparison whose operand contains a multiplication of two
non-constant values must instead be division-form (``n > cap / width``) or
carry an explicit overflow guard — a prior division by one of the
multiplicands in the same function counts, as does ``// noqa: PT903`` with a
reason. ``for (...)`` headers are exempt (loop-bound arithmetic over
already-validated counts, not untrusted-input capacity checks).

**PT904 — unguarded memcpy / pointer-advance.** A ``memcpy`` whose
destination is a buffer (not an address-of scalar local) and whose length
is computed (not a parameter/constant the caller already bounded) must be
dominated by a bounds comparison in the same function that names the
destination's capacity — the specific capacity field when the destination
is a fused-ABI descriptor pointer (``out`` → ``out_cap``, ``aux_buf`` →
``aux_cap``, ``chunk`` → ``chunk_len``), a capacity-like token
(``cap``/``len``/``size``/``bytes``/``avail``/``end``/``total``) otherwise.
Likewise a pointer that advances (``p += n``) inside a loop must be compared
against an end/bound in the same function. Dropping the check while keeping
the copy is exactly the PR 6 ``aux_bufs`` class.

Scope: ``native/*.cpp``. Suppress with ``// noqa: PT903`` / ``// noqa:
PT904`` on the finding's line (reason encouraged). See ``docs/analysis.md``.
"""

from __future__ import annotations

import re

from petastorm_tpu.analysis.buffers import (_match_brace,
                                            _strip_cpp_comments_and_strings)
from petastorm_tpu.analysis.core import Checker

#: a C++ function definition head (loose; shared shape with buffers.PT502)
_CPP_DEF_RE = re.compile(
    r'^[ \t]*(?:[A-Za-z_][\w:<>,*&\s]*?[\s*&])?'
    r'(?:[A-Za-z_][\w]*::)?(?P<name>~?[A-Za-z_]\w*)\s*\([^;{}]*\)'
    r'(?:\s*const)?(?:\s*noexcept)?\s*\{', re.MULTILINE)

_CPP_KEYWORDS = {'if', 'for', 'while', 'switch', 'return', 'catch', 'sizeof',
                 'defined'}

#: a comparison operator with the codebase's mandatory surrounding spaces —
#: distinguishes bounds checks from template brackets (``std::min<uint64_t>``)
_CMP_RE = re.compile(r'\s(?:<=|>=|<|>)\s')

#: ``A * B`` where both operands are value expressions (identifiers, casts,
#: member chains) — a literal factor still wraps for a huge counterpart, so
#: literals are NOT exempt; pointer-deref stars never have space on both sides
_MUL_RE = re.compile(
    r'(?P<lhs>[\w\)\]](?:[\w\.\)\]]|->)*)\s\*\s(?P<rhs>[\w\(]+)')

#: identifier tokens that read as a capacity/bound (PT904 generic tier)
_CAP_TOKEN_RE = re.compile(
    r'\b\w*(cap|capacity|len|size|bytes|avail|bound|end|total)\w*\b',
    re.IGNORECASE)

#: fused-ABI descriptor pointer field -> its capacity field (specific tier)
_DESC_BOUND_FIELDS = {'out': 'out_cap', 'aux_buf': 'aux_cap',
                      'chunk': 'chunk_len'}

_MEMCPY_RE = re.compile(r'\b(?:std::)?mem(?:cpy|move)\s*\(')

#: local/param pointer declaration: ``const uint8_t* p`` / ``uint8_t *dst``
_PTR_DECL_RE = re.compile(
    r'\b(?:const\s+)?[A-Za-z_][\w:]*\s*\*\s*(?:const\s*)?([A-Za-z_]\w*)\s*[=,;)]')

_PTR_ADVANCE_RE = re.compile(r'\b([A-Za-z_]\w*)\s*\+=\s*([^;]+);')


def _function_bodies(text):
    """(name, start_line, body_text including the signature) for every
    function definition in ``text`` (comments/strings already stripped)."""
    out = []
    for m in _CPP_DEF_RE.finditer(text):
        name = m.group('name')
        if name in _CPP_KEYWORDS:
            continue
        open_brace = text.index('{', m.end() - 1)
        end = _match_brace(text, open_brace)
        if end is None:
            continue
        lineno = text.count('\n', 0, m.start()) + 1
        out.append((name, lineno, text[m.start():end + 1]))
    return out


def _split_args(call_args):
    """Top-level comma split of a call's argument text."""
    parts, depth, cur = [], 0, []
    for ch in call_args:
        if ch in '([':
            depth += 1
        elif ch in ')]':
            depth -= 1
        if ch == ',' and depth == 0:
            parts.append(''.join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append(''.join(cur).strip())
    return parts


def _param_names(body):
    """Parameter (and template-parameter) names of a function body that
    begins with its signature."""
    sig_end = body.index('{')
    sig = body[:sig_end]
    open_paren = sig.find('(')
    if open_paren < 0:
        return set()
    names = set()
    for p in _split_args(sig[open_paren + 1:sig.rfind(')')]):
        m = re.search(r'([A-Za-z_]\w*)\s*$', p)
        if m:
            names.add(m.group(1))
    return names


def _identifiers(expr):
    return set(re.findall(r'[A-Za-z_]\w*', expr))


class CppSafetyChecker(Checker):
    code = 'PT903'
    codes = ('PT903', 'PT904')
    name = 'cpp-overflow-bounds'
    description = ('multiplication-form bounds comparisons that can wrap '
                   '(PT903); memcpy/pointer-advance without a dominating '
                   'capacity check (PT904)')
    scope = ('*native/*.cpp', '*native/*.cc')

    def check(self, src):
        text = _strip_cpp_comments_and_strings(src.text)
        for name, lineno, body in _function_bodies(text):
            yield from self._check_mul_bounds(src, name, lineno, body)
            yield from self._check_memcpy_bounds(src, name, lineno, body)
            yield from self._check_pointer_advances(src, name, lineno, body)

    # -- PT903 ---------------------------------------------------------------

    #: cast/type tokens that are never the value factor of a product
    _CAST_TOKENS = frozenset({'uint64_t', 'int64_t', 'uint32_t', 'int32_t',
                              'size_t', 'int', 'unsigned', 'long', 'sizeof',
                              'static_cast', 'u', 'ull', 'll', 'ul'})

    _INT_LITERAL_RE = re.compile(r'^\(?\d+(?:[uUlL]*)\)?$')

    def _check_mul_bounds(self, src, fn_name, fn_line, body):
        lines = body.split('\n')
        for i, line in enumerate(lines):
            stripped = line.strip()
            if stripped.startswith('for'):
                continue  # loop headers: counts already validated upstream
            if not _CMP_RE.search(line):
                continue
            for mm in _MUL_RE.finditer(line):
                lhs, rhs = mm.group('lhs'), mm.group('rhs')
                if self._INT_LITERAL_RE.match(lhs) or self._INT_LITERAL_RE.match(rhs):
                    continue  # constant factor: the hostile class is value*value
                factors = {t for t in _identifiers(lhs) | _identifiers(rhs)
                           if t not in self._CAST_TOKENS and not t.isdigit()}
                if not factors:
                    continue
                if self._factors_guarded(body, line, factors):
                    continue
                yield self.finding(
                    src, fn_line + i,
                    'multiplication-form bounds comparison in {}() — a corrupt '
                    'value wraps {} * {} past the check; compare division-form '
                    '(a > cap / b) or guard the product explicitly'.format(
                        fn_name, lhs.strip(')'), rhs.strip('(')),
                    code='PT903')

    def _factors_guarded(self, body, mul_line, factors):
        """The overflow guard this rule accepts: EVERY factor individually
        capped against a non-zero literal elsewhere in the function
        (``w > (1u << 24)``-style magnitude gates). One capped factor is not
        enough — the unbounded one still wraps the product; a division-form
        check elsewhere is not enough either — it bounds a *different*
        occurrence of the variable (the shipped dictionary-page bug lived in
        a branch its sibling check never dominated)."""
        def capped(tok):
            for line in body.split('\n'):
                if line is mul_line:
                    continue
                m = re.search(r'\b{}\b\s*(?:<|<=|>|>=)\s*\(?\s*(\d+)'
                              .format(re.escape(tok)), line)
                if m and int(m.group(1)) != 0:
                    return True
            return False
        return all(capped(tok) for tok in factors)

    # -- PT904: memcpy dominance ---------------------------------------------

    def _check_memcpy_bounds(self, src, fn_name, fn_line, body):
        params = _param_names(body)
        for m in _MEMCPY_RE.finditer(body):
            close = self._call_end(body, m.end() - 1)
            if close is None:
                continue
            args = _split_args(body[m.end():close])
            if len(args) != 3:
                continue
            dest, _src_arg, length = args
            if dest.startswith('&'):
                continue  # address-of scalar local: fixed-size, in-frame
            length_ids = _identifiers(length) - {'sizeof', 'uint64_t', 'int64_t',
                                                 'size_t', 'int'}
            if length_ids and length_ids <= params:
                continue  # the bound travels in as a parameter: caller checked
            if not length_ids and not re.search(r'[A-Za-z_]', length):
                continue  # pure constant length
            lineno = fn_line + body.count('\n', 0, m.start())
            required = self._required_cap_tokens(dest)
            if required is not None:
                if not any(re.search(r'\b{}\b'.format(tok), body)
                           for tok in required):
                    yield self.finding(
                        src, lineno,
                        'memcpy into descriptor pointer {} in {}() with no '
                        'check naming its capacity field {} — the PR 6 '
                        'aux-misalignment class'.format(
                            dest, fn_name, '/'.join(required)),
                        code='PT904')
                continue
            if not self._has_cap_comparison(body):
                yield self.finding(
                    src, lineno,
                    'memcpy in {}() with a computed length and no bounds '
                    'comparison naming a capacity in the function — every '
                    'write at the native boundary must be dominated by the '
                    "destination's capacity check".format(fn_name),
                    code='PT904')

    @staticmethod
    def _call_end(body, open_paren):
        depth = 0
        for i in range(open_paren, len(body)):
            if body[i] == '(':
                depth += 1
            elif body[i] == ')':
                depth -= 1
                if depth == 0:
                    return i
        return None

    @staticmethod
    def _required_cap_tokens(dest):
        """The specific capacity field(s) a fused-ABI descriptor destination
        must be checked against, or None for the generic tier."""
        for field, cap in _DESC_BOUND_FIELDS.items():
            if re.search(r'(->|\.){}\b'.format(field), dest):
                return (cap,)
        return None

    @staticmethod
    def _has_cap_comparison(body):
        for line in body.split('\n'):
            if not _CMP_RE.search(line) and '?' not in line:
                continue
            if _CAP_TOKEN_RE.search(line):
                return True
        return False

    # -- PT904: pointer advances ----------------------------------------------

    def _check_pointer_advances(self, src, fn_name, fn_line, body):
        pointers = set(_PTR_DECL_RE.findall(body))
        if not pointers:
            return
        params = _param_names(body)
        cmp_lines = [line for line in body.split('\n') if _CMP_RE.search(line)]
        for m in _PTR_ADVANCE_RE.finditer(body):
            name, amount = m.group(1), m.group(2)
            if name not in pointers:
                continue
            amount_ids = _identifiers(amount) - {'sizeof', 'uint64_t',
                                                 'int64_t', 'size_t'}
            if amount_ids and amount_ids <= params and name in params:
                continue  # caller-bounded walk over caller-owned memory
            # dominated either by a comparison involving the pointer itself
            # (`p < end`, `end - p < n`) or by comparisons validating every
            # identifier the advance amount is computed from
            ptr_checked = any(re.search(r'\b{}\b'.format(re.escape(name)), line)
                              for line in cmp_lines)
            amount_checked = amount_ids and all(
                any(re.search(r'\b{}\b'.format(re.escape(tok)), line)
                    for line in cmp_lines)
                for tok in amount_ids)
            if not ptr_checked and not amount_checked:
                lineno = fn_line + body.count('\n', 0, m.start())
                yield self.finding(
                    src, lineno,
                    'pointer {} advances in {}() with no bounds comparison '
                    'against an end/capacity in the function — a corrupt '
                    'length walks it out of the buffer'.format(name, fn_name),
                    code='PT904')
