"""Linter framework: source loading, findings, ``# noqa`` and baseline support.

Design notes:

* A :class:`SourceFile` pairs a file's text with its parsed AST (Python) —
  C++ sources (the native kernels) carry text only and are consumed by the
  text-level rules in :mod:`buffers`.
* Scoping is per-checker via fnmatch patterns against the file's *relative*
  path, so unit tests can exercise a checker on a fixture by constructing a
  ``SourceFile`` with any relpath they like (e.g. ``workers/fake.py``).
* Suppression matches the existing codebase convention: ``# noqa: CODE`` (with
  an optional free-text reason after the code list) on the finding's line, or a
  bare ``# noqa`` suppressing every rule on that line. ``BLE001`` — the
  broad-except code the tree already annotates — is honored as an alias for
  PT300, so the pre-reviewed handlers need no re-annotation.
* Baselines absorb findings by ``(code, path, stripped line text)`` with
  multiplicity, NOT by line number — a baseline survives unrelated edits above
  the finding.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

#: noqa comment: "# noqa" or "# noqa: PT100" or "# noqa: PT100,BLE001 - reason"
_NOQA_RE = re.compile(r'#\s*noqa(?::\s*(?P<codes>[A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*))?',
                      re.IGNORECASE)

#: foreign suppression codes accepted for our equivalent rule
_CODE_ALIASES = {'BLE001': 'PT300'}


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a source location.

    ``status`` is ``'open'`` for an actionable finding; runs with
    ``keep_suppressed=True`` also carry ``'noqa'`` (suppressed on its line)
    and ``'baselined'`` (absorbed by the baseline) findings so machine
    consumers (``--format json``) can annotate diffs with the full picture.
    """
    path: str       # relative path (as scoped/reported)
    line: int       # 1-based
    code: str       # e.g. 'PT100'
    message: str
    snippet: str = field(default='', compare=False)
    status: str = field(default='open', compare=False)

    def format(self):
        return '{}:{}: {} {}'.format(self.path, self.line, self.code, self.message)

    def to_dict(self):
        """The stable one-object-per-line JSON schema of ``--format json``."""
        return {'rule': self.code, 'path': self.path, 'line': self.line,
                'message': self.message, 'snippet': self.snippet,
                'status': self.status}


class SourceFile(object):
    """A loaded source file: text, lines, per-line noqa codes, and (for
    Python) the parsed AST with parent links."""

    def __init__(self, path, relpath, text):
        self.path = path
        self.relpath = relpath.replace(os.sep, '/')
        self.text = text
        self.lines = text.splitlines()
        self.is_python = relpath.endswith('.py')
        self.tree = None
        self.parse_error = None
        self._noqa = (self._collect_noqa(text) if self.is_python
                      else self._collect_noqa_cpp(text))
        if self.is_python:
            try:
                self.tree = ast.parse(text)
            except SyntaxError as e:
                self.parse_error = e

    @classmethod
    def load(cls, path, relpath):
        with open(path, 'rb') as f:
            raw = f.read()
        try:
            text = raw.decode('utf-8')
        except UnicodeDecodeError:
            text = raw.decode('latin-1')
        return cls(path, relpath, text)

    @staticmethod
    def _collect_noqa(text):
        """{line: set of codes | None} — None means a bare ``# noqa`` (all).
        Tokenized, not regexed over raw lines, so a '# noqa' inside a string
        literal does not suppress anything."""
        noqa = {}
        try:
            tokens = tokenize.generate_tokens(iter(text.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _NOQA_RE.search(tok.string)
                if not m:
                    continue
                codes = m.group('codes')
                if codes is None:
                    noqa[tok.start[0]] = None
                else:
                    parsed = {c.strip().upper() for c in codes.split(',')}
                    parsed |= {_CODE_ALIASES[c] for c in parsed if c in _CODE_ALIASES}
                    existing = noqa.get(tok.start[0], set())
                    noqa[tok.start[0]] = None if existing is None else existing | parsed
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass
        return noqa

    @staticmethod
    def _collect_noqa_cpp(text):
        """C++ flavor: ``// noqa: PT903 - reason`` line comments (the C++
        rules PT502/PT9xx report on these sources)."""
        noqa = {}
        for i, line in enumerate(text.splitlines(), 1):
            comment = line.split('//', 1)
            if len(comment) < 2:
                continue
            m = _NOQA_RE.search('#' + comment[1])
            if not m:
                continue
            codes = m.group('codes')
            noqa[i] = None if codes is None else \
                {c.strip().upper() for c in codes.split(',')}
        return noqa

    def is_suppressed(self, line, code):
        if line not in self._noqa:
            return False
        codes = self._noqa[line]
        return codes is None or code in codes

    def line_text(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ''


class Checker(object):
    """Base of every rule family.

    Subclasses set ``code`` (the family id used in docs/CLI listings),
    ``name``, ``description``, and ``scope`` — fnmatch patterns over relative
    paths (a leading ``*`` keeps them working whether or not the scanned root
    includes the ``petastorm_tpu/`` prefix). ``check(src)`` yields
    :class:`Finding` objects; noqa/baseline filtering happens in the runner.
    """

    code = 'PT000'
    #: every rule id the checker can emit (None = just ``code``); the linter
    #: meta-test requires a committed bad/clean fixture pair per listed id,
    #: so a new id registered here without teeth fails tier-1
    codes = None
    #: True on :class:`ProgramChecker` subclasses — run once over all
    #: matching sources, not once per file
    program_level = False
    name = 'base'
    description = ''
    scope = ('*.py',)

    @classmethod
    def rule_codes(cls):
        return cls.codes or (cls.code,)

    def matches_path(self, relpath):
        import fnmatch
        return any(fnmatch.fnmatch(relpath, pat)
                   or fnmatch.fnmatch('/' + relpath, pat) for pat in self.scope)

    def matches(self, src):
        return self.matches_path(src.relpath)

    def check(self, src):
        raise NotImplementedError

    def finding(self, src, line, message, code=None):
        return Finding(path=src.relpath, line=line, code=code or self.code,
                       message=message, snippet=src.line_text(line))


class ProgramChecker(Checker):
    """Base of whole-program rule families (the PT13xx race lints).

    A program checker sees every in-scope source at once via
    :meth:`check_program` — cross-module lock-order graphs and guarded-by
    inference cannot be computed one file at a time. ``check(src)`` delegates
    to a single-file program run so fixture unit tests keep working, and the
    runner (:func:`run_checkers`) takes care to invoke the checker exactly
    once per pass, never per file. Incremental runs cache the program pass
    under a digest of ALL in-scope file bytes (see
    :func:`petastorm_tpu.analysis.cache.run_analysis_incremental`)."""

    #: dispatch marker honored by run_checkers and the incremental runner
    program_level = True

    def check_program(self, sources):
        raise NotImplementedError

    def check(self, src):
        yield from self.check_program([src])


class Baseline(object):
    """Known-findings ledger: entries keyed by (code, path, stripped line
    text) with multiplicity. Line numbers are deliberately absent."""

    def __init__(self, entries=None):
        self._counts = {}
        for e in entries or []:
            key = self._key(e['code'], e['path'], e['line_text'])
            self._counts[key] = self._counts.get(key, 0) + int(e.get('count', 1))

    @staticmethod
    def _key(code, path, line_text):
        return (code, path, line_text.strip())

    def absorb(self, findings):
        """Findings not covered by the baseline (consumes multiplicity)."""
        return self.split(findings)[0]

    def split(self, findings):
        """``(open, absorbed)`` — absorbed findings carry status
        ``'baselined'`` (consumes multiplicity, like :meth:`absorb`)."""
        from dataclasses import replace
        remaining = dict(self._counts)
        open_findings, absorbed = [], []
        for f in findings:
            key = self._key(f.code, f.path, f.snippet)
            if remaining.get(key, 0) > 0:
                remaining[key] -= 1
                absorbed.append(replace(f, status='baselined'))
            else:
                open_findings.append(f)
        return open_findings, absorbed

    @staticmethod
    def from_findings(findings):
        counts = {}
        for f in findings:
            key = (f.code, f.path, f.snippet.strip())
            counts[key] = counts.get(key, 0) + 1
        return [{'code': c, 'path': p, 'line_text': t, 'count': n}
                for (c, p, t), n in sorted(counts.items())]


def load_baseline(path):
    """Load an ``analysis_baseline.json`` (``{"version": 1, "entries": [...]}``
    or a bare entries list). Returns an empty :class:`Baseline` for a missing
    file so fresh checkouts need no placeholder."""
    if not path or not os.path.exists(path):
        return Baseline()
    with open(path) as f:
        data = json.load(f)
    entries = data['entries'] if isinstance(data, dict) else data
    return Baseline(entries)


def write_baseline(path, findings):
    with open(path, 'w') as f:
        json.dump({'version': 1, 'entries': Baseline.from_findings(findings)}, f,
                  indent=2, sort_keys=True)
        f.write('\n')


#: extensions the framework loads; checkers scope further
_SOURCE_EXTS = ('.py', '.cpp', '.cc', '.h', '.hpp')

#: directories never scanned
_SKIP_DIRS = {'__pycache__', '.git', '.pytest_cache', 'node_modules'}


def collect_sources(paths):
    """Load every source file under ``paths`` (files and/or directories).
    Relative paths are taken against each directory argument (so scanning
    ``petastorm_tpu/`` yields ``workers/thread_pool.py``-style relpaths)."""
    sources = []
    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            sources.append(SourceFile.load(root, os.path.basename(root)))
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(_SOURCE_EXTS):
                    full = os.path.join(dirpath, fn)
                    sources.append(SourceFile.load(full, os.path.relpath(full, root)))
    return sources


def run_checkers(checkers, sources, baseline=None, keep_suppressed=False):
    """Apply ``checkers`` to ``sources``; returns sorted findings with noqa
    suppression and baseline absorption applied. Python files that fail to
    parse produce a single PT000 finding (the pass must not silently skip).

    ``keep_suppressed=True`` keeps noqa'd/baselined findings in the result,
    annotated via :attr:`Finding.status` (``'noqa'``/``'baselined'``) — the
    machine-readable mode behind ``--format json``; only ``'open'`` findings
    are actionable either way."""
    from dataclasses import replace
    findings = []
    suppressed = []
    per_file = [c for c in checkers if not c.program_level]
    program = [c for c in checkers if c.program_level]
    by_relpath = {}
    for src in sources:
        if src.parse_error is not None:
            findings.append(Finding(path=src.relpath, line=src.parse_error.lineno or 1,
                                    code='PT000',
                                    message='syntax error: {}'.format(src.parse_error.msg)))
            continue
        by_relpath[src.relpath] = src
        for checker in per_file:
            if not checker.matches(src):
                continue
            for f in checker.check(src):
                if not src.is_suppressed(f.line, f.code):
                    findings.append(f)
                elif keep_suppressed:
                    suppressed.append(replace(f, status='noqa'))
    # whole-program passes: one invocation over every matching (parseable)
    # source; noqa still applies at the reported line of the reported file
    for checker in program:
        in_scope = [s for s in sources if s.parse_error is None
                    and checker.matches(s)]
        if not in_scope:
            continue
        for f in checker.check_program(in_scope):
            src = by_relpath.get(f.path)
            if src is None or not src.is_suppressed(f.line, f.code):
                findings.append(f)
            elif keep_suppressed:
                suppressed.append(replace(f, status='noqa'))
    findings.sort()
    if baseline is not None:
        open_findings, absorbed = baseline.split(findings)
        findings = open_findings + (absorbed if keep_suppressed else [])
    if keep_suppressed:
        findings = sorted(findings + suppressed)
    return findings


# -- shared AST helpers used by several checkers ----------------------------

def add_parents(tree):
    """Annotate every node with ``.pt_parent`` (None on the root)."""
    tree.pt_parent = None
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.pt_parent = node
    return tree


def attr_chain(node):
    """Dotted name of an Attribute/Name chain ('self._lock', 'np.random.rand'),
    or None when the chain contains calls/subscripts."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def class_methods(classdef):
    """The directly-defined function bodies of a class (no nesting descent)."""
    return [n for n in classdef.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def walk_functions(tree):
    """Every function/method in the module, with its enclosing class (or None)."""
    out = []

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, cls))
                visit(child, cls)
            else:
                visit(child, cls)

    visit(tree, None)
    return out
