"""PT1100–PT1103 — borrow-checking the shared-memory plane.

The zero-copy read path hands consumers *borrows*: views whose bytes belong
to a producer-owned resource with its own reclamation schedule — an shm-ring
message slot (``ShmRing.try_read_zero_copy``), a COW-mapped serve/pool blob
(``_map_blob``/``_read_blob``), a chunkstore mirror (``mmap_chunk``), or a
pagescan zero-copy column view. The runtime half
(``petastorm_tpu/native/lifetime.py``) accounts every borrow through a slot
registry; this module is the static half — it proves, at lint time, that no
borrow leaks past the registry:

**PT1100** a borrow is stored into longer-lived state (``self.x``, a
container cell, a module global) in a function that never touches the
lifetime registry. The store outlives the frame, so nothing ties the view's
death to the slot's refcount — the runtime cannot see the borrow and will
reclaim under it.

**PT1101** a function *returns* a borrow without a ``:borrows:`` marker in
its docstring. Returning is a legitimate hand-off, but the caller inherits
the lifetime obligation — the convention (docs/analysis.md) is that every
borrow-returning function documents it with a ``:borrows:`` docstring
section, so the obligation is visible at every call site's definition.

**PT1102** a borrow crosses a process or serialization boundary —
``pickle.dumps``, ``queue.put``, a zmq ``send*``, a ring ``try_write``/
``publish`` — without being copied (``bytes()``, ``.copy()``,
``.tobytes()``, ``bytearray()``) first. The bytes on the wire would alias
memory the producer reclaims on its own schedule; the receiver gets torn
data (or a guard fault) with no local cause.

**PT1103** a borrow's release is not dominated: the function calls a
releaser (``release``/``close``/``seal``/``release_now``/``drop``/``end``)
on the borrow, but only on *some* paths — inside a conditional, outside any
``finally``, and the borrow is not a ``with`` context. An exception (or the
untaken branch) then leaks the slot's refcount and wedges the ring's FIFO
release ledger. Same shape as PT700's span hygiene, applied to borrows.
"""

from __future__ import annotations

import ast
import re

from petastorm_tpu.analysis.core import Checker, add_parents, attr_chain, walk_functions

#: call names whose result is a borrow of shared-plane memory.  NOT here:
#: ``try_read_view``/``read_view`` (fresh per-message ctypes buffer, owned by
#: the view chain) and ``scan_mirrored_chunk`` (a page *plan* — offsets, not
#: memory).
_BORROW_CALLS = {
    'try_read_zero_copy',            # ShmRing: view straight into the ring slot
    '_map_blob', '_read_blob',       # serve/pool blob COW mappings
    'mmap_chunk',                    # chunkstore mirror mapping
    'read_mirrored_chunk', 'read_columns_zerocopy',  # views over mirrors/pool
    'memmap', 'mmap',                # raw np.memmap / mmap.mmap maps
}

#: wrapper calls that preserve borrow-ness (the result still aliases the
#: same memory); everything else consuming the value as an argument derives
#: fresh data or takes over the obligation
_VIEW_WRAPPERS = {'memoryview', 'frombuffer'}

#: attribute calls on a borrow that still alias the same memory
_VIEW_METHODS = {'reshape', 'cast', 'view', 'transpose', 'swapaxes', 'squeeze',
                 'ravel'}

#: copy-laundering: these produce owned data from a borrow
_COPY_CALLS = {'bytes', 'bytearray', 'list', 'loads'}
_COPY_METHODS = {'copy', 'tobytes', 'decode'}

#: serialization/process-boundary sinks (PT1102)
_BOUNDARY_METHODS = {'dumps', 'put', 'put_nowait', 'send', 'send_multipart',
                     'send_pyobj', 'publish', 'try_write', 'reserve_write'}

#: releaser methods whose call on a borrow marks manual lifetime management
_RELEASERS = {'release', 'release_now', 'close', 'seal', 'drop', 'end',
              '__exit__'}

#: a function mentioning the lifetime-registry API is handing its borrows to
#: the runtime half — registration is the sanctioned way to store a borrow
_REGISTRY_RE = re.compile(
    r'\b(open_slot|adopt|retain|RingBorrowLedger|lifetime_registry|'
    r'lifetime\.registry|registry\(\)|close_when_drained)\b')


def _call_name(node):
    """The bare callable name of ``node`` (``np.memmap`` -> 'memmap')."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_borrow_call(node):
    return _call_name(node) in _BORROW_CALLS


def _expr_carries_borrow(expr, borrow_names):
    """True when evaluating ``expr`` yields something aliasing a borrow: a
    designated borrow call, a borrow name, or either of those passed through
    view-preserving wrappers/slices — and NOT laundered through a copy."""
    for node in ast.walk(expr):
        is_source = _is_borrow_call(node) or (
            isinstance(node, ast.Name) and node.id in borrow_names)
        if not is_source:
            continue
        if not _laundered_on_path(node, expr):
            return True
    return False


def _laundered_on_path(node, stop):
    """Climb from ``node`` to ``stop``: True when some enclosing expression
    copies the value or consumes it as an argument of a non-view call."""
    cur = node
    while cur is not stop:
        parent = getattr(cur, 'pt_parent', None)
        if parent is None:
            return False
        if isinstance(parent, ast.Attribute) and parent.attr in _COPY_METHODS:
            return True
        if isinstance(parent, ast.Compare):
            return True  # the value is a bool, not the view
        if isinstance(parent, ast.IfExp) and cur is parent.test:
            return True  # tested, not propagated
        if isinstance(parent, ast.Call) and cur is not parent.func:
            name = _call_name(parent)
            if name in _COPY_CALLS:
                return True
            if name not in _VIEW_WRAPPERS:
                return True  # consumed by some other call: obligation moves
        if isinstance(parent, ast.Call) and cur is parent.func:
            if isinstance(cur, ast.Attribute) and cur.attr in _COPY_METHODS:
                return True
            if isinstance(cur, ast.Attribute) and cur.attr not in _VIEW_METHODS:
                return True  # .sum()/.astype()/...: fresh data
        cur = parent
    return False


def _borrow_bindings(fn):
    """Names bound (directly or by tuple unpack) to a borrow-source call."""
    names = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not _contains_borrow_call(node.value):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                names.add(target.id)
            elif isinstance(target, (ast.Tuple, ast.List)):
                # (view, slot) = _map_blob(...): conservatively treat every
                # bound name as carrying the borrow
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        names.add(elt.id)
    return names


def _contains_borrow_call(expr):
    return any(_is_borrow_call(n) for n in ast.walk(expr))


def _conditional_ancestors(node, fn):
    """Statement-level ancestors of ``node`` below ``fn`` that make its
    execution conditional (If/While/For/Try bodies; a ``finally`` suite does
    not count — it always runs)."""
    out = []
    cur = getattr(node, 'pt_parent', None)
    child = node
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.If, ast.While, ast.For, ast.AsyncFor)):
            out.append(cur)
        elif isinstance(cur, ast.Try):
            if not any(child is s or _is_descendant(child, s)
                       for s in cur.finalbody):
                out.append(cur)
        child = cur
        cur = getattr(cur, 'pt_parent', None)
    return out


def _is_descendant(node, root):
    return any(n is node for n in ast.walk(root))


def _in_finally(node, fn):
    cur = getattr(node, 'pt_parent', None)
    child = node
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.Try) and any(
                child is s or _is_descendant(child, s) for s in cur.finalbody):
            return True
        child = cur
        cur = getattr(cur, 'pt_parent', None)
    return False


def _used_as_context(fn, name):
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id == name:
                    return True
    return False


class LifetimeChecker(Checker):
    code = 'PT1100'
    codes = ('PT1100', 'PT1101', 'PT1102', 'PT1103')
    name = 'shared-plane-borrow-check'
    description = ('borrows of shared-plane memory (ring slots, blob maps, '
                   'chunk mirrors) stored unregistered, returned undeclared, '
                   'serialized across a boundary, or released only on some '
                   'paths')
    scope = ('*native/*.py', '*workers/*.py', '*serve/*.py',
             '*chunkstore/*.py', '*jax/*.py', '*serializers.py')

    def check(self, src):
        if not src.is_python:
            return
        add_parents(src.tree)
        seen = set()  # a closure's body is walked under its enclosing
        for fn, _cls in walk_functions(src.tree):  # function too: dedupe
            for f in self._check_function(src, fn):
                if (f.line, f.code) not in seen:
                    seen.add((f.line, f.code))
                    yield f

    def _check_function(self, src, fn):
        borrow_names = _borrow_bindings(fn)
        has_direct = any(_is_borrow_call(n) for n in ast.walk(fn))
        if not borrow_names and not has_direct:
            return
        seg = ast.get_source_segment(src.text, fn) or ''
        registers = bool(_REGISTRY_RE.search(seg))
        yield from self._check_stores(src, fn, borrow_names, registers)
        yield from self._check_returns(src, fn, borrow_names)
        yield from self._check_boundaries(src, fn, borrow_names)
        if not registers:
            # a function handing its borrows to the lifetime registry has
            # delegated release to the runtime half — the registry's
            # finalizers dominate every exit, so path analysis is moot
            yield from self._check_release_domination(src, fn, borrow_names)

    # -- PT1100: stored into longer-lived state without registration --------

    def _check_stores(self, src, fn, borrow_names, registers):
        if registers:
            return
        global_names = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                global_names.update(node.names)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not _expr_carries_borrow(node.value, borrow_names):
                continue
            for target in node.targets:
                escapes = (isinstance(target, (ast.Attribute, ast.Subscript))
                           or (isinstance(target, ast.Name)
                               and target.id in global_names))
                if escapes:
                    yield self.finding(
                        src, node.lineno,
                        'borrow of shared-plane memory stored into longer-lived '
                        'state in {}() without registering with the lifetime '
                        'registry (native/lifetime.py) — the runtime cannot see '
                        'this reference and will reclaim the bytes under it'
                        .format(fn.name))
                    break

    # -- PT1101: returned without a :borrows: docstring marker --------------

    def _check_returns(self, src, fn, borrow_names):
        doc = ast.get_docstring(fn) or ''
        if ':borrows:' in doc:
            return
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Return, ast.Yield)) or node.value is None:
                continue
            if _expr_carries_borrow(node.value, borrow_names):
                yield self.finding(
                    src, node.lineno,
                    '{}() returns a borrow of shared-plane memory without a '
                    '":borrows:" docstring section — the caller inherits the '
                    'lifetime obligation and must be able to see it '
                    '(docs/analysis.md)'.format(fn.name),
                    code='PT1101')
                return

    # -- PT1102: crosses a process/serialization boundary -------------------

    def _check_boundaries(self, src, fn, borrow_names):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name not in _BOUNDARY_METHODS:
                continue
            if name == 'dumps':
                chain = attr_chain(node.func) or ''
                if not chain.startswith('pickle'):
                    continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                carries = any(
                    (isinstance(n, ast.Name) and n.id in borrow_names
                     and not _laundered_on_path(n, arg))
                    or (_is_borrow_call(n) and not _laundered_on_path(n, arg))
                    for n in ast.walk(arg))
                if carries:
                    yield self.finding(
                        src, node.lineno,
                        'borrow of shared-plane memory crosses a process/'
                        'serialization boundary via {}() in {}() — the wire '
                        'bytes alias producer-owned memory; copy first '
                        '(bytes()/.tobytes()/.copy())'.format(name, fn.name),
                        code='PT1102')
                    break

    # -- PT1103: release not dominated on all paths -------------------------

    def _check_release_domination(self, src, fn, borrow_names):
        for bname in sorted(borrow_names):
            if _used_as_context(fn, bname):
                continue
            releasers = [
                node for node in ast.walk(fn)
                if isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RELEASERS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == bname]
            if not releasers:
                continue  # lifetime handed off (registry/ledger), not manual
            if any(_in_finally(node, fn) for node in releasers):
                continue
            if any(not _conditional_ancestors(node, fn) for node in releasers):
                continue  # a straight-line release dominates the exits
            yield self.finding(
                src, releasers[0].lineno,
                "borrow '{}' in {}() is released only on some paths (every "
                'releaser call sits inside a conditional, none in a finally) '
                '— the untaken branch or an exception leaks the slot refcount '
                'and wedges the FIFO release ledger'.format(bname, fn.name),
                code='PT1103')
