"""PT702 — autotune action discipline.

The autotuner exists to change a RUNNING pipeline's configuration, which is
exactly why its writes must be disciplined: a knob move that leaves no trace
is an unexplainable config change ("the autotuner changed my config — why?"
is a documented troubleshooting entry), and a knob write that skips the clamp
can push a pool or budget outside the bounds the user set. Both failure modes
are lexically checkable, so this rule checks them:

* every call to a knob **actuator** (``add_worker_slot``,
  ``retire_worker_slot``, ``set_prefetch_budget``, ``set_shuffle_capacity``,
  ``set_max_queue_size``, ``resize``) inside ``petastorm_tpu/autotune/`` must
  sit lexically inside a ``with decision_span(...)`` (or ``obs.span(...)``)
  block — the change then lands in the trace ring as an ``autotune.decision``
  event next to the code that made it;
* every **value** passed to a value-bearing actuator must come from
  ``clamp(...)`` — either directly at the call site or via a name assigned
  from a ``clamp(...)`` call in the same function. Constants, raw arithmetic
  and config reads are rejected: the bounds live in one place and every write
  must pass through them.

The rule scopes to the autotune package only: the actuators themselves are
DEFINED elsewhere (pools, loader, chunk-cache config) and called freely by
tests and user code — the discipline applies to the controller, the one
caller that moves knobs autonomously.
"""

from __future__ import annotations

import ast

from petastorm_tpu.analysis.core import Checker, add_parents, walk_functions

#: knob actuators: calls that change a running pipeline's configuration
_ACTUATORS = frozenset({'add_worker_slot', 'retire_worker_slot',
                        'set_prefetch_budget', 'set_shuffle_capacity',
                        'set_max_queue_size', 'resize'})

#: actuators whose arguments are knob values and must be clamp-derived
_VALUE_ACTUATORS = frozenset({'set_prefetch_budget', 'set_shuffle_capacity',
                              'resize'})

#: span-context callables that satisfy the wrapping requirement
_SPAN_OPENERS = frozenset({'decision_span', 'span', 'stage'})


def _call_name(call):
    func = call.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _inside_decision_span(node, stop_at):
    """Is ``node`` lexically inside a ``with`` whose context expression opens
    a span (``decision_span(...)`` / ``obs.span(...)``), before ``stop_at``?"""
    cur = node
    while cur is not None and cur is not stop_at:
        parent = getattr(cur, 'pt_parent', None)
        if isinstance(parent, (ast.With, ast.AsyncWith)):
            for item in parent.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and _call_name(expr) in _SPAN_OPENERS:
                    return True
        cur = parent
    return False


def _clamp_assigned_names(func):
    """Names assigned from a ``clamp(...)`` call anywhere in ``func``."""
    names = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _call_name(node.value) == 'clamp':
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
    return names


def _is_clamped(arg, clamped_names):
    if isinstance(arg, ast.Call) and _call_name(arg) == 'clamp':
        return True
    return isinstance(arg, ast.Name) and arg.id in clamped_names


class AutotuneActionChecker(Checker):
    code = 'PT702'
    name = 'autotune-action-discipline'
    description = ('autotune knob actuations must be decision_span-wrapped '
                   'and pass their values through clamp() — unexplained or '
                   'unbounded knob writes are rejected')
    scope = ('*autotune/*.py',)

    def check(self, src):
        add_parents(src.tree)
        for func, _cls in walk_functions(src.tree):
            clamped = None  # lazy: most functions touch no actuator
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                if name not in _ACTUATORS:
                    continue
                if not _inside_decision_span(node, func):
                    yield self.finding(
                        src, node.lineno,
                        '{}() called outside a decision_span: the knob change '
                        'would leave no autotune.decision event to explain '
                        'it'.format(name))
                if name in _VALUE_ACTUATORS:
                    if clamped is None:
                        clamped = _clamp_assigned_names(func)
                    values = list(node.args) + [kw.value for kw in node.keywords]
                    for arg in values:
                        if not _is_clamped(arg, clamped):
                            yield self.finding(
                                src, node.lineno,
                                '{}() takes a value that did not pass through '
                                'clamp(): knob writes must be bounded by the '
                                "config's explicit [min, max]".format(name))
                            break
