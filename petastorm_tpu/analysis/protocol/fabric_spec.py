"""Executable spec of the chunk-fabric transfer protocol (``docs/fabric.md``).

``petastorm_tpu/fabric`` lets a host that misses a chunk fetch it from a pod
peer's mirror before touching the object store: peer-first with sha256
verification, a per-peer circuit breaker, and an unconditional object-store
fallback. This module states that design as an explicit-state transition
system small enough to check exhaustively — the same treatment PR 5 gave the
supervision protocol, PR 9 the serve fan-out, and PR 14 elastic resharding.

Model scope (one fetching host, ``peers`` serving peers, ``chunks`` chunk
fetches in flight):

* a peer is UP or CRASHED; a crashed peer's lease has not expired yet, so
  requests still route to it and fail (connect refused) — exactly the
  window the breaker exists for;
* network faults (refused / reset / truncated / corrupt payloads) come from
  small budgets; resets, truncations and refusals collapse into one
  "transient failure" transition because the client classifies them
  identically, while corruption is separate (it exercises the hash gate);
* the breaker is modeled per peer as (state, consecutive failures); the
  open→half-open cooldown is a *transition*, time abstracted to structure;
* verification and population collapse into the request-resolution
  transitions: ``req_ok`` is verified bytes populating the mirror,
  ``req_corrupt`` is bytes failing the hash (discarded — unless the
  ``skip_hash_check`` mutation lets them through).

Checked invariants (catalog order; ``docs/protocol.md``):

* ``populate_once`` — a chunk is populated at most once on this host;
* ``hash_verified`` — fetched bytes always hash-verify or are discarded
  (no poisoned mirror);
* ``breaker_discipline`` — a peer whose breaker is open receives no
  requests (judged at admission: a breaker opening mid-flight on an
  already-issued request is NOT a violation);
* ``fetch_termination`` — every fetch terminates via peer bytes, fallback
  bytes, or a surfaced error, under any combination of crashes, faults,
  and fallback failures.

Mutations re-introduce one defect each so the checker's teeth are testable:
``skip_hash_check`` (corrupt payloads populate the mirror), ``double_populate``
(a completed fetch can populate again — the single-flight guard removed),
``request_open_peer`` (admission ignores the breaker), ``no_fallback``
(a failed peer fetch strands the chunk instead of degrading).
"""

from __future__ import annotations

import collections
import random
import time

# peer liveness
UP, CRASHED = 0, 1

# breaker states (mirrors fabric/breaker.py)
B_CLOSED, B_OPEN, B_HALF = 0, 1, 2

#: the checked invariants, in catalog order (docs/protocol.md)
INVARIANTS = (
    'populate_once',
    'hash_verified',
    'breaker_discipline',
    'fetch_termination',
)

#: seedable spec defects proving the checker has teeth
MUTATIONS = (
    'skip_hash_check',
    'double_populate',
    'request_open_peer',
    'no_fallback',
)

# state tuple indices
CHUNKS, PEERS, CRASHES_LEFT, FAULTS_LEFT, FB_FAILS_LEFT, FLAGS = range(6)

# flags bitmask
F_OPEN_REQ = 1      # a request was admitted to an open-breaker peer
F_DOUBLE = 2        # a chunk was populated twice
F_POISON = 4        # unverified bytes reached the mirror

# chunk cell encoding, for cfg.peers == P:
#   PEND (0)        fetch not started
#   1 + p           request in flight to peer p
#   1 + P           fallback (object-store read) in flight
#   2 + P           done: populated from a peer
#   3 + P           done: populated from the fallback
#   4 + P           done: fallback failed, error surfaced to the caller
#   5 + P           stuck: peer failed and nothing degraded (mutant sink)
PEND = 0


class FabricSpecConfig(object):
    """Small-scope configuration.

    :param peers: serving peers visible to the fetching host
    :param chunks: chunk fetches in the run
    :param crashes: peer-crash budget
    :param faults: transient-network-fault budget (refused/reset/truncated
        payloads AND corrupt payloads draw from it)
    :param fb_fails: object-store fallback failure budget
    :param breaker_k: consecutive failures that open a peer's breaker
    :param mutation: one of :data:`MUTATIONS`, or None for the real protocol
    """

    __slots__ = ('peers', 'chunks', 'crashes', 'faults', 'fb_fails',
                 'breaker_k', 'mutation')

    def __init__(self, peers=2, chunks=3, crashes=1, faults=2, fb_fails=1,
                 breaker_k=2, mutation=None):
        if peers < 1 or chunks < 1:
            raise ValueError('empty scope parameter')
        if crashes < 0 or faults < 0 or fb_fails < 0:
            raise ValueError('negative event budget')
        if breaker_k < 1:
            raise ValueError('breaker_k must be >= 1')
        if mutation is not None and mutation not in MUTATIONS:
            raise ValueError('unknown mutation {!r} (expected one of {})'.format(
                mutation, MUTATIONS))
        self.peers = peers
        self.chunks = chunks
        self.crashes = crashes
        self.faults = faults
        self.fb_fails = fb_fails
        self.breaker_k = breaker_k
        self.mutation = mutation

    def describe(self):
        return ('peers={} chunks={} crashes={} faults={} fb_fails={} '
                'breaker_k={}{}'.format(
                    self.peers, self.chunks, self.crashes, self.faults,
                    self.fb_fails, self.breaker_k,
                    ' mutation={}'.format(self.mutation)
                    if self.mutation else ''))


def initial_state(cfg):
    peers = tuple((UP, 0, B_CLOSED) for _ in range(cfg.peers))
    return ((PEND,) * cfg.chunks, peers, cfg.crashes, cfg.faults,
            cfg.fb_fails, 0)


def canonicalize(state):
    """Peers are NOT interchangeable (rendezvous ranking keys on identity),
    so canonical form is the state itself."""
    return state


def _cells(cfg):
    P = cfg.peers
    return {'fb': 1 + P, 'done_peer': 2 + P, 'done_fb': 3 + P,
            'done_err': 4 + P, 'stuck': 5 + P}


def _set_chunk(state, c, value):
    chunks = state[CHUNKS][:c] + (value,) + state[CHUNKS][c + 1:]
    return (chunks,) + state[1:]


def _set_peer(state, p, peer):
    peers = state[PEERS][:p] + (peer,) + state[PEERS][p + 1:]
    return state[:PEERS] + (peers,) + state[PEERS + 1:]


def _spend(state, idx):
    return state[:idx] + (state[idx] - 1,) + state[idx + 1:]


def _flag(state, bit):
    return state[:FLAGS] + (state[FLAGS] | bit,)


def _peer_success(state, p):
    return _set_peer(state, p, (state[PEERS][p][0], 0, B_CLOSED))


def _peer_failure(state, p, cfg):
    up, failures, breaker = state[PEERS][p]
    failures += 1
    if breaker == B_HALF or failures >= cfg.breaker_k:
        breaker = B_OPEN
    return _set_peer(state, p, (up, failures, breaker))


def successors(state, cfg):
    """All enabled transitions as (label, canonical next state) pairs."""
    out = []
    P = cfg.peers
    cells = _cells(cfg)
    FB, DONE_PEER, DONE_FB = cells['fb'], cells['done_peer'], cells['done_fb']
    DONE_ERR, STUCK = cells['done_err'], cells['stuck']
    chunks = state[CHUNKS]
    peers = state[PEERS]

    for c, cell in enumerate(chunks):
        # start: admission picks any breaker-admitted peer (the real client
        # picks the rendezvous-best one; any admitted peer exercises the
        # same protocol), or goes straight to the fallback when none is
        if cell == PEND:
            any_admitted = False
            for p, (up, _f, breaker) in enumerate(peers):
                if breaker != B_OPEN:
                    any_admitted = True
                    out.append((('start', c, p, True),
                                _set_chunk(state, c, 1 + p)))
                elif cfg.mutation == 'request_open_peer':
                    # the defect: admission ignores the breaker entirely
                    out.append((('start', c, p, False),
                                _flag(_set_chunk(state, c, 1 + p),
                                      F_OPEN_REQ)))
            if not any_admitted:
                out.append((('start', c, None, True),
                            _set_chunk(state, c, FB)))

        # request resolution
        elif 1 <= cell <= P:
            p = cell - 1
            up = peers[p][0] == UP
            fail_target = STUCK if cfg.mutation == 'no_fallback' else FB
            if up:
                # verified bytes populate the mirror; breaker resets
                out.append((('req_ok', c, p),
                            _peer_success(_set_chunk(state, c, DONE_PEER), p)))
                if state[FAULTS_LEFT] > 0:
                    # transient failure (refused / reset / truncated): the
                    # client classifies them identically -> one transition
                    out.append((('req_fail', c, p),
                                _peer_failure(_spend(
                                    _set_chunk(state, c, fail_target),
                                    FAULTS_LEFT), p, cfg)))
                    # corrupt payload: hash gate discards it (a failure) —
                    # unless the skip_hash_check defect lets it populate
                    if cfg.mutation == 'skip_hash_check':
                        out.append((('req_corrupt', c, p, True),
                                    _flag(_spend(
                                        _set_chunk(state, c, DONE_PEER),
                                        FAULTS_LEFT), F_POISON)))
                    else:
                        out.append((('req_corrupt', c, p, False),
                                    _peer_failure(_spend(
                                        _set_chunk(state, c, fail_target),
                                        FAULTS_LEFT), p, cfg)))
            else:
                # crashed peer, lease not yet expired: connect refused
                out.append((('req_fail', c, p),
                            _peer_failure(
                                _set_chunk(state, c, fail_target), p, cfg)))

        # fallback resolution
        elif cell == FB:
            out.append((('fb_ok', c), _set_chunk(state, c, DONE_FB)))
            if state[FB_FAILS_LEFT] > 0:
                out.append((('fb_fail', c),
                            _spend(_set_chunk(state, c, DONE_ERR),
                                   FB_FAILS_LEFT)))

        # the double_populate defect: a completed fetch populates again
        # (the single-flight guard removed)
        elif cell in (DONE_PEER, DONE_FB) and \
                cfg.mutation == 'double_populate':
            out.append((('double', c), _flag(state, F_DOUBLE)))

    # peer crash (SIGKILL mid-anything; its lease lives on for a while)
    if state[CRASHES_LEFT] > 0:
        for p, (up, failures, breaker) in enumerate(peers):
            if up == UP:
                out.append((('crash', p),
                            _spend(_set_peer(state, p,
                                             (CRASHED, failures, breaker)),
                                   CRASHES_LEFT)))

    # breaker cooldown: open -> half-open (time abstracted to structure)
    for p, (up, failures, breaker) in enumerate(peers):
        if breaker == B_OPEN:
            out.append((('cooldown', p),
                        _set_peer(state, p, (up, failures, B_HALF))))

    return [(label, canonicalize(ns)) for label, ns in out]


def check_state(state, cfg):
    """First violated safety invariant, or None."""
    flags = state[FLAGS]
    if flags & F_DOUBLE:
        return 'populate_once'
    if flags & F_POISON:
        return 'hash_verified'
    if flags & F_OPEN_REQ:
        return 'breaker_discipline'
    return None


def check_terminal(state, cfg):
    """Liveness at quiescence: every fetch must have resolved — peer bytes,
    fallback bytes, or a surfaced error. A stranded chunk (the no_fallback
    mutant's sink) is exactly the hang this invariant forbids."""
    cells = _cells(cfg)
    done = (cells['done_peer'], cells['done_fb'], cells['done_err'])
    if any(cell not in done for cell in state[CHUNKS]):
        return 'fetch_termination'
    return None


class FabricCheckResult(object):
    __slots__ = ('config', 'exhausted', 'states', 'transitions', 'depth',
                 'elapsed_s', 'violation', 'trace', 'terminal_states')

    def __init__(self, config):
        self.config = config
        self.exhausted = False
        self.states = 0
        self.transitions = 0
        self.depth = 0
        self.elapsed_s = 0.0
        self.violation = None
        self.trace = None
        self.terminal_states = 0

    @property
    def ok(self):
        return self.exhausted and self.violation is None

    def to_dict(self):
        return {'config': self.config.describe(), 'exhausted': self.exhausted,
                'states': self.states, 'transitions': self.transitions,
                'depth': self.depth, 'elapsed_s': round(self.elapsed_s, 3),
                'terminal_states': self.terminal_states,
                'violation': self.violation,
                'trace': [repr(l) for l in self.trace] if self.trace else None}


def check(cfg, budget_s=None, max_states=None):
    """Exhaustive BFS over every interleaving of the fabric transfer system.
    BFS order makes the first counterexample length-minimal."""
    result = FabricCheckResult(cfg)
    t0 = time.monotonic()
    init = canonicalize(initial_state(cfg))
    parents = {init: None}
    frontier = collections.deque([(init, 0)])
    result.states = 1
    violation, violating = check_state(init, cfg), None
    if violation:
        violating = init
    popped = 0
    while frontier and violation is None:
        state, depth = frontier.popleft()
        popped += 1
        result.depth = max(result.depth, depth)
        succ = successors(state, cfg)
        result.transitions += len(succ)
        if not succ:
            result.terminal_states += 1
            violation = check_terminal(state, cfg)
            if violation:
                violating = state
                break
        for label, ns in succ:
            if ns in parents:
                continue
            parents[ns] = (state, label)
            result.states += 1
            v = check_state(ns, cfg)
            if v is not None:
                violation, violating = v, ns
                break
            frontier.append((ns, depth + 1))
        if violation is None and popped % 2048 == 0:
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                break
            if max_states is not None and result.states >= max_states:
                break
    else:
        if violation is None:
            result.exhausted = True
    result.elapsed_s = time.monotonic() - t0
    if violation is not None:
        result.violation = violation
        trace = []
        s = violating
        while parents[s] is not None:
            s, label = parents[s]
            trace.append(label)
        trace.reverse()
        result.trace = trace
    return result


def random_walk(cfg, seed, max_steps=200):
    """One seeded schedule through the system: the trace walked and whether
    it ended in a violating state. Drives the monitor-conformance fuzz in
    ``tests/test_fabric.py``."""
    rng = random.Random(seed)
    state = initial_state(cfg)
    trace = []
    violation = check_state(state, cfg)
    for _ in range(max_steps):
        if violation is not None:
            break
        succ = successors(state, cfg)
        if not succ:
            violation = check_terminal(state, cfg)
            break
        label, state = succ[rng.randrange(len(succ))]
        trace.append(label)
        violation = check_state(state, cfg)
    return trace, violation


def replay_into_monitor(trace, monitor):
    """Replay a spec trace through a :class:`~petastorm_tpu.analysis.
    protocol.monitor.FabricMonitor` — the event-projection glue that keeps
    the runtime monitor honest against the spec. Healthy traces must pass;
    mutant traces that reach an event-visible defect must raise
    :class:`~petastorm_tpu.errors.ProtocolViolation`. (``no_fallback`` is a
    liveness defect with no event to observe — the model checker, not the
    monitor, owns it.)"""
    for label in trace:
        kind = label[0]
        if kind == 'start' and label[2] is not None:
            monitor.on_request('peer{}'.format(label[2]), allowed=label[3])
        elif kind == 'req_ok':
            monitor.on_populate('chunk{}'.format(label[1]), verified=True)
            monitor.on_outcome('chunk{}'.format(label[1]), 'peer')
        elif kind == 'req_corrupt' and label[3]:
            # the skip_hash_check mutant: unverified bytes hit the mirror
            monitor.on_populate('chunk{}'.format(label[1]), verified=False)
        elif kind == 'fb_ok':
            monitor.on_populate('chunk{}'.format(label[1]), verified=True)
            monitor.on_outcome('chunk{}'.format(label[1]), 'fallback')
        elif kind == 'fb_fail':
            monitor.on_outcome('chunk{}'.format(label[1]), 'error')
        elif kind == 'double':
            monitor.on_populate('chunk{}'.format(label[1]), verified=True)
        # 'req_fail', 'crash', 'cooldown' have no mirror-visible event


#: the tier-1 default scope (tests/test_fabric.py gates exhaustion + a
#: state floor on it, like the supervision, serve, and elastic scopes)
DEFAULT_FABRIC_SCOPE = dict(peers=3, chunks=4, crashes=2, faults=3,
                            fb_fails=2, breaker_k=2)

#: the default scope must explore at least this many canonical states — the
#: regression tripwire against accidental transition pruning (the scope
#: above explores ~435k)
DEFAULT_FABRIC_STATE_FLOOR = 200_000

__all__ = ['DEFAULT_FABRIC_SCOPE', 'DEFAULT_FABRIC_STATE_FLOOR',
           'FabricCheckResult', 'FabricSpecConfig', 'INVARIANTS',
           'MUTATIONS', 'canonicalize', 'check', 'check_state',
           'check_terminal', 'initial_state', 'random_walk',
           'replay_into_monitor', 'successors']
