"""Worker-pool protocol verifier: executable spec, exhaustive small-scope
model checker, and runtime conformance monitor (``docs/protocol.md``).

* :mod:`spec` — the supervision protocol (dispatch-id ownership, claim
  heartbeats, two-stage death handling, stale dropping, quiet-window sweep)
  as an explicit-state transition system with its five invariants stated as
  predicates.
* :mod:`modelcheck` — BFS over all interleavings for small configurations
  with canonical state hashing and counterexample minimization; the
  ``petastorm-tpu-modelcheck`` console script and the tier-1 budgeted test.
* :mod:`monitor` — the opt-in runtime hook the pools feed their observed
  events through; any sequence the spec rejects raises
  :class:`~petastorm_tpu.errors.ProtocolViolation`.

The PT8xx protocol lints (non-exhaustive kind dispatch, constants defined
outside ``workers/protocol.py``) live in
:mod:`petastorm_tpu.analysis.protocol_lints` with the other rule families.
"""

from __future__ import annotations

from petastorm_tpu.analysis.protocol.modelcheck import (CheckResult, check,
                                                        format_trace, minimize_trace)
from petastorm_tpu.analysis.protocol.monitor import (ProtocolMonitor,
                                                     ProtocolViolation, monitor_from_env)
from petastorm_tpu.analysis.protocol.spec import (INVARIANTS, MUTATIONS, SpecConfig,
                                                  replay_into_monitor, replay_trace)

__all__ = [
    'CheckResult', 'INVARIANTS', 'MUTATIONS', 'ProtocolMonitor',
    'ProtocolViolation', 'SpecConfig', 'check', 'format_trace',
    'minimize_trace', 'monitor_from_env', 'replay_into_monitor', 'replay_trace',
]
