"""Executable spec of the serve fan-out protocol (multi-consumer invariants).

The broadcast ring (``native/shm_ring.cpp`` ``pstpu_bcast_*``) is what makes
the shared reader daemon (``docs/serve.md``) trustworthy: a published batch is
logically reference-counted across K attached consumers by *min-head
reclamation* — each consumer's cursor advance is its release, and a slot's
bytes are reclaimed when the slowest attached cursor passes them. This module
states that design as an explicit-state transition system small enough to
check exhaustively, the same treatment PR 5 gave the supervision protocol.

Model scope:

* messages are whole batches (the ring's byte arithmetic is abstracted to a
  capacity of ``ring_cap`` in-flight messages);
* joins happen at the producer's current position (the implementation grants
  slots daemon-side between writes — the ``join_stale_cursor`` mutation is
  exactly what that design rules out);
* eviction is *enabled* (not forced) whenever an attached consumer's lag
  exceeds ``lag_bound`` — time is abstracted to structure, as in ``spec.py``;
* an evicted slot stops constraining reclamation and must never be delivered
  to again (the seqlock validation in ``pstpu_bcast_read``).

Checked invariants (catalog order; ``docs/protocol.md``):

* ``released_exactly_once_per_consumer`` — no attached consumer instance is
  ever delivered the same batch twice;
* ``no_overwritten_read`` — no consumer is delivered a batch whose slot the
  producer had already reclaimed (a torn read);
* ``evicted_never_delivered`` — an evicted consumer receives nothing further;
* ``tenant_epoch_termination`` — at quiescence every still-attached consumer
  has received EXACTLY the batches published since its attach point: detach
  and eviction of others lose nothing and double-deliver nothing for the
  consumers that remain.

Mutations re-introduce one defect each so the checker's teeth are testable:
``reclaim_ignores_slowest`` (free-space scan skips the most-lagged consumer —
the min-head bug), ``evict_keeps_delivering`` (reads keep working after
eviction — the missing seqlock validation), ``join_stale_cursor`` (a joiner
snapshots its cursor racily at 0 — the join-outside-the-write-lock bug).
"""

from __future__ import annotations

import collections
import time

# consumer slot states
FREE, ATTACHED, EVICTED = 0, 1, 2

#: the checked invariants, in catalog order (docs/protocol.md)
INVARIANTS = (
    'released_exactly_once_per_consumer',
    'no_overwritten_read',
    'evicted_never_delivered',
    'tenant_epoch_termination',
)

#: seedable spec defects proving the checker has teeth
MUTATIONS = (
    'reclaim_ignores_slowest',
    'evict_keeps_delivering',
    'join_stale_cursor',
)

# state tuple: (published, slots)
# slot tuple: (state, attach_at, cursor, delivered, violated_flags)
#   delivered: sorted tuple of message indices this instance received
S_STATE, S_ATTACH, S_CURSOR, S_DELIVERED, S_FLAGS = range(5)


class ServeSpecConfig(object):
    """Small-scope configuration.

    :param messages: batches the producer will publish for the stream
    :param slots: consumer slots (symmetric; canonicalization exploits this)
    :param attaches: attach-event budget (instances over the run)
    :param detaches: graceful-detach budget
    :param ring_cap: in-flight message capacity of the broadcast ring
    :param lag_bound: eviction becomes enabled when a consumer lags more than
        this many messages behind the producer
    :param mutation: one of :data:`MUTATIONS`, or None for the real protocol
    """

    __slots__ = ('messages', 'slots', 'attaches', 'detaches', 'ring_cap',
                 'lag_bound', 'mutation')

    def __init__(self, messages=4, slots=3, attaches=4, detaches=1,
                 ring_cap=2, lag_bound=1, mutation=None):
        if messages < 1 or slots < 1 or attaches < 1 or ring_cap < 1:
            raise ValueError('empty scope parameter')
        if mutation is not None and mutation not in MUTATIONS:
            raise ValueError('unknown mutation {!r} (expected one of {})'.format(
                mutation, MUTATIONS))
        self.messages = messages
        self.slots = slots
        self.attaches = attaches
        self.detaches = detaches
        self.ring_cap = ring_cap
        self.lag_bound = lag_bound
        self.mutation = mutation

    def describe(self):
        return ('messages={} slots={} attaches={} detaches={} ring_cap={} '
                'lag_bound={}{}'.format(
                    self.messages, self.slots, self.attaches, self.detaches,
                    self.ring_cap, self.lag_bound,
                    ' mutation={}'.format(self.mutation) if self.mutation else ''))


def initial_state(cfg):
    slot = (FREE, 0, 0, (), ())
    return (0, (slot,) * cfg.slots, cfg.attaches, cfg.detaches)

# extended state tuple: (published, slots, attach_budget, detach_budget)
PUBLISHED, SLOTS, ATTACH_BUDGET, DETACH_BUDGET = range(4)


def canonicalize(state):
    """Slots are interchangeable: sort them."""
    return (state[PUBLISHED], tuple(sorted(state[SLOTS])),
            state[ATTACH_BUDGET], state[DETACH_BUDGET])


def _reclaim_horizon(state, cfg):
    """First message index still guaranteed live in the ring: everything
    below ``published - ring_cap`` may have been reclaimed UNLESS an attached
    cursor pins it. With the real protocol the producer never publishes past
    an attached cursor + ring_cap, so the horizon equals
    ``min(attached cursors)`` when any consumer is attached."""
    published = state[PUBLISHED]
    cursors = [s[S_CURSOR] for s in state[SLOTS] if s[S_STATE] == ATTACHED]
    if not cursors:
        return published
    return min(cursors)


def _publish_enabled(state, cfg):
    if state[PUBLISHED] >= cfg.messages:
        return False
    cursors = [s[S_CURSOR] for s in state[SLOTS] if s[S_STATE] == ATTACHED]
    if cfg.mutation == 'reclaim_ignores_slowest' and len(cursors) > 1:
        cursors.remove(min(cursors))  # the defect: the slowest does not count
    floor = min(cursors) if cursors else state[PUBLISHED]
    return state[PUBLISHED] - floor < cfg.ring_cap


def _set_slot(state, i, slot):
    slots = state[SLOTS][:i] + (slot,) + state[SLOTS][i + 1:]
    return (state[PUBLISHED], slots, state[ATTACH_BUDGET], state[DETACH_BUDGET])


def successors(state, cfg):
    """All enabled transitions as (label, canonical next state) pairs."""
    out = []
    published = state[PUBLISHED]
    slots = state[SLOTS]

    # producer: publish the next batch (bounded by the slowest attached cursor)
    if _publish_enabled(state, cfg):
        out.append((('publish', published),
                    (published + 1, slots, state[ATTACH_BUDGET],
                     state[DETACH_BUDGET])))

    horizon = published - cfg.ring_cap  # oldest physically retained index
    for i, s in enumerate(slots):
        st = s[S_STATE]
        if st == FREE and state[ATTACH_BUDGET] > 0:
            # attach: cursor snapshots the producer position (daemon-side
            # grant); the mutation snapshots a stale 0 instead
            cursor = 0 if cfg.mutation == 'join_stale_cursor' else published
            ns = _set_slot(state, i, (ATTACHED, cursor, cursor, (), ()))
            ns = (ns[PUBLISHED], ns[SLOTS], ns[ATTACH_BUDGET] - 1,
                  ns[DETACH_BUDGET])
            out.append((('attach', i, cursor), ns))
        if st == ATTACHED:
            if s[S_CURSOR] < published:
                # read: deliver the cursor message and advance. A read below
                # the physical horizon is a torn read (flagged, not hidden).
                m = s[S_CURSOR]
                flags = s[S_FLAGS]
                if m < published - cfg.ring_cap:
                    flags = tuple(sorted(set(flags) | {'torn'}))
                delivered = tuple(sorted(s[S_DELIVERED] + (m,)))
                ns = _set_slot(state, i, (ATTACHED, s[S_ATTACH], m + 1,
                                          delivered, flags))
                out.append((('deliver', i, m), ns))
            if state[DETACH_BUDGET] > 0:
                # graceful detach: the instance's record is dropped (it left
                # voluntarily); remaining consumers must be unaffected
                ns = _set_slot(state, i, (FREE, 0, 0, (), ()))
                ns = (ns[PUBLISHED], ns[SLOTS], ns[ATTACH_BUDGET],
                      ns[DETACH_BUDGET] - 1)
                out.append((('detach', i), ns))
            if published - s[S_CURSOR] > cfg.lag_bound:
                # eviction enabled (never forced): the slot stops counting
                ns = _set_slot(state, i, (EVICTED, s[S_ATTACH], s[S_CURSOR],
                                          s[S_DELIVERED], s[S_FLAGS]))
                out.append((('evict', i), ns))
        if st == EVICTED and cfg.mutation == 'evict_keeps_delivering' \
                and s[S_CURSOR] < published:
            # the defect: the missing seqlock validation lets an evicted
            # consumer keep reading reclaimed slots
            m = s[S_CURSOR]
            delivered = tuple(sorted(s[S_DELIVERED] + (m,)))
            flags = tuple(sorted(set(s[S_FLAGS]) | {'evicted_read'}))
            ns = _set_slot(state, i, (EVICTED, s[S_ATTACH], m + 1, delivered,
                                      flags))
            out.append((('deliver_evicted', i, m), ns))

    return [(label, canonicalize(ns)) for label, ns in out]


def check_state(state, cfg):
    """First violated safety invariant, or None."""
    for s in state[SLOTS]:
        delivered = s[S_DELIVERED]
        if len(delivered) != len(set(delivered)):
            return 'released_exactly_once_per_consumer'
        if 'torn' in s[S_FLAGS]:
            return 'no_overwritten_read'
        if 'evicted_read' in s[S_FLAGS]:
            return 'evicted_never_delivered'
    return None


def check_terminal(state, cfg):
    """'tenant_epoch_termination' when a quiescent state leaves any attached
    consumer short of (or beyond) its window [attach_at, messages)."""
    if state[PUBLISHED] != cfg.messages:
        return 'tenant_epoch_termination'  # quiescent but unpublished: stuck
    for s in state[SLOTS]:
        if s[S_STATE] != ATTACHED:
            continue
        expected = tuple(range(s[S_ATTACH], cfg.messages))
        if s[S_DELIVERED] != expected:
            return 'tenant_epoch_termination'
    return None


class ServeCheckResult(object):
    __slots__ = ('config', 'exhausted', 'states', 'transitions', 'depth',
                 'elapsed_s', 'violation', 'trace', 'terminal_states')

    def __init__(self, config):
        self.config = config
        self.exhausted = False
        self.states = 0
        self.transitions = 0
        self.depth = 0
        self.elapsed_s = 0.0
        self.violation = None
        self.trace = None
        self.terminal_states = 0

    @property
    def ok(self):
        return self.exhausted and self.violation is None

    def to_dict(self):
        return {'config': self.config.describe(), 'exhausted': self.exhausted,
                'states': self.states, 'transitions': self.transitions,
                'depth': self.depth, 'elapsed_s': round(self.elapsed_s, 3),
                'terminal_states': self.terminal_states,
                'violation': self.violation,
                'trace': [repr(l) for l in self.trace] if self.trace else None}


def check(cfg, budget_s=None, max_states=None):
    """Exhaustive BFS over every interleaving of the serve fan-out system.
    BFS order makes the first counterexample length-minimal."""
    result = ServeCheckResult(cfg)
    t0 = time.monotonic()
    init = canonicalize(initial_state(cfg))
    parents = {init: None}
    frontier = collections.deque([(init, 0)])
    result.states = 1
    violation, violating = check_state(init, cfg), None
    if violation:
        violating = init
    popped = 0
    while frontier and violation is None:
        state, depth = frontier.popleft()
        popped += 1
        result.depth = max(result.depth, depth)
        succ = successors(state, cfg)
        result.transitions += len(succ)
        if not succ:
            result.terminal_states += 1
            violation = check_terminal(state, cfg)
            if violation:
                violating = state
                break
        for label, ns in succ:
            if ns in parents:
                continue
            parents[ns] = (state, label)
            result.states += 1
            v = check_state(ns, cfg)
            if v is not None:
                violation, violating = v, ns
                break
            frontier.append((ns, depth + 1))
        if violation is None and popped % 2048 == 0:
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                break
            if max_states is not None and result.states >= max_states:
                break
    else:
        if violation is None:
            result.exhausted = True
    result.elapsed_s = time.monotonic() - t0
    if violation is not None:
        result.violation = violation
        trace = []
        s = violating
        while parents[s] is not None:
            s, label = parents[s]
            trace.append(label)
        trace.reverse()
        result.trace = trace
    return result


#: the tier-1 default scope (tests/test_serve.py gates exhaustion + a state
#: floor on it, like the supervision scope in tests/test_protocol.py):
#: ~944k canonical states, ~20s on the reference container
DEFAULT_SERVE_SCOPE = dict(messages=7, slots=4, attaches=7, detaches=3,
                           ring_cap=3, lag_bound=2)

#: the default scope must explore at least this many canonical states — the
#: regression tripwire against accidental transition pruning (the real count
#: sits near 944k)
DEFAULT_SERVE_STATE_FLOOR = 200_000

__all__ = ['DEFAULT_SERVE_SCOPE', 'DEFAULT_SERVE_STATE_FLOOR', 'INVARIANTS',
           'MUTATIONS', 'ServeCheckResult',
           'ServeSpecConfig', 'canonicalize', 'check', 'check_state',
           'check_terminal', 'initial_state', 'successors']
