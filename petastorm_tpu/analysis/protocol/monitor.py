"""Runtime conformance monitor for the worker-pool supervision protocol.

The observer projection of ``spec.py``: a :class:`ProtocolMonitor` ingests the
consumer-visible events of a live pool — dispatches, requeues, consumed
messages with the pool's live/stale classification, completions, epoch drains
— and raises :class:`~petastorm_tpu.errors.ProtocolViolation` on any sequence
the spec rejects. Where the model checker proves the *design* for small
scopes, the monitor checks that the *implementation* actually walks the
spec's transition relation on every real run (ThreadSanitizer-style: the
checking rides the workload you already run).

Opt in per pool (``ProcessPool(..., protocol_monitor=True)``), per reader
(``make_reader(..., protocol_monitor=True)``), or process-wide via
``PSTPU_PROTOCOL_MONITOR=1`` — which is how ``tests/test_fault_tolerance.py``
and the ``--protocol-monitor`` bench flags turn every existing crash /
requeue / poison scenario into a conformance proof. Overhead is one guarded
method call per *item-level* event (never per row); with the monitor off the
pools pay a single ``None`` check.

Event rules (the spec's conformance contract, ``docs/protocol.md``):

* dispatch ids are issued monotonically and NEVER reused;
* a requeue must take a live id out of flight and issue a fresh one — and must
  never requeue an item whose payload was already delivered (that is the
  double-delivery defect the model checker surfaces as ``requeue_published``);
* every consumed message must reference an issued id, and the pool's
  live/stale classification must match the monitor's in-flight view;
* each logical item (a dispatch-id chain linked by requeues) completes at most
  once, and only from a live id;
* at epoch drain the pool's ventilated/completed counters must equal the
  monitor's, with nothing left in flight.
"""

from __future__ import annotations

import threading

from petastorm_tpu.errors import ProtocolViolation


class ProtocolMonitor(object):
    """Thread-safe conformance monitor (pools emit events from consumer and
    worker threads). All state is dispatch-id keyed, so it works for the
    process pool's wire protocol and the thread/dummy pools' in-process
    equivalent alike."""

    def __init__(self, name='pool'):
        self._name = name
        self._lock = threading.Lock()
        self._last_id = -1
        self._inflight = {}    # live dispatch id -> root id (logical item chain)
        self._resolved = {}    # retired/completed dispatch id -> root id
        self._published = set()  # live ids whose payload reached the consumer
        self._completed_roots = set()
        self._seq_by_root = {}
        self.ventilated = 0
        self.completed = 0
        self.violations_checked = 0

    def _fail(self, message):
        raise ProtocolViolation('[protocol monitor: {}] {}'.format(self._name, message))

    def _fresh(self, d, what):
        if d in self._inflight or d in self._resolved:
            self._fail('{} reuses dispatch id {} — ids must never be reused or '
                       'stale messages become indistinguishable from live ones'
                       .format(what, d))
        if d <= self._last_id:
            self._fail('{} issued non-monotonic dispatch id {} (last was {})'
                       .format(what, d, self._last_id))
        self._last_id = d

    # -- events --------------------------------------------------------------

    def on_dispatch(self, d, seq=None):
        """A new item was ventilated under dispatch id ``d``."""
        with self._lock:
            self.violations_checked += 1
            self._fresh(d, 'dispatch')
            self._inflight[d] = d
            self._seq_by_root[d] = seq
            self.ventilated += 1

    def on_requeue(self, old_d, new_d):
        """An in-flight item moved from ``old_d`` to a fresh ``new_d``."""
        with self._lock:
            self.violations_checked += 1
            root = self._inflight.get(old_d)
            if root is None:
                self._fail('requeue of dispatch id {} which is not in flight '
                           '(stale or never issued)'.format(old_d))
            if old_d in self._published:
                self._fail('requeue of dispatch id {} whose payload was already '
                           'delivered — re-running it would deliver the item '
                           'twice'.format(old_d))
            self._fresh(new_d, 'requeue')
            del self._inflight[old_d]
            self._resolved[old_d] = root
            self._inflight[new_d] = root

    def on_message(self, kind, d, live=None):
        """The consumer processed a ``kind`` message for dispatch ``d``.
        ``live`` is the pool's stale/live classification (None when the kind
        carries no such decision, e.g. claims)."""
        if d is None:
            return  # untagged message (startup, idle beacon): nothing to check
        with self._lock:
            self.violations_checked += 1
            known = d in self._inflight or d in self._resolved
            if not known:
                self._fail('{} message for dispatch id {} which was never '
                           'issued'.format(kind, d))
            if live is True and d not in self._inflight:
                self._fail('pool treated a {} for retired dispatch id {} as '
                           'live — stale stragglers must be dropped'.format(kind, d))
            if live is False and d in self._inflight:
                self._fail('pool dropped a {} for live dispatch id {} as '
                           'stale'.format(kind, d))
            if kind == 'data' and live:
                self._published.add(d)

    def on_complete(self, d, delivered, quarantined=False):
        """The pool resolved dispatch ``d`` (done consumed / orphan published /
        quarantine / error-completion) and advanced its completion counter."""
        with self._lock:
            self.violations_checked += 1
            root = self._inflight.pop(d, None)
            if root is None:
                self._fail('completion for dispatch id {} which is not in '
                           'flight — a stale duplicate must not advance the '
                           'epoch accounting'.format(d))
            self._resolved[d] = root
            self._published.discard(d)
            if root in self._completed_roots:
                self._fail('item (root dispatch {}, seq {}) completed twice'
                           .format(root, self._seq_by_root.get(root)))
            self._completed_roots.add(root)
            self.completed += 1

    def on_drained(self, pool_ventilated, pool_completed):
        """The pool declared the epoch drained (``EmptyResultError``)."""
        with self._lock:
            self.violations_checked += 1
            if self._inflight:
                self._fail('epoch declared drained with {} dispatch id(s) still '
                           'in flight: {}'.format(
                               len(self._inflight), sorted(self._inflight)))
            if (pool_ventilated, pool_completed) != (self.ventilated, self.completed):
                self._fail('pool counters (ventilated={}, completed={}) diverge '
                           'from observed events (ventilated={}, completed={})'
                           .format(pool_ventilated, pool_completed,
                                   self.ventilated, self.completed))
            if pool_ventilated != pool_completed:
                self._fail('drained epoch with ventilated={} != completed={}'
                           .format(pool_ventilated, pool_completed))

    @property
    def snapshot(self):
        """Diagnostics view: counters + in-flight ids (for test assertions)."""
        with self._lock:
            return {'ventilated': self.ventilated, 'completed': self.completed,
                    'in_flight': sorted(self._inflight),
                    'events_checked': self.violations_checked}


class ServeMonitor(object):
    """Runtime conformance monitor for the serve fan-out plane
    (``docs/serve.md``, multi-consumer invariant catalog in
    ``docs/protocol.md``). Each process checks its observable projection of
    the broadcast protocol:

    * daemon side — a tenant attaches at most once and only detaches/evicts
      while attached; a stream never publishes the SAME item seq twice (a
      repeat means a batch was decoded-and-published twice: the retry path
      may reorder seqs, but never duplicate them); nothing is published to a
      stream after its END;
    * consumer side — no seq is delivered twice to this consumer (a duplicate
      means a ring slot was re-delivered: the released-exactly-once-per-
      consumer invariant broken), and nothing is delivered after the
      stream's END frame.

    Violations raise :class:`~petastorm_tpu.errors.ProtocolViolation`.
    """

    def __init__(self, name='serve'):
        self._name = name
        self._lock = threading.Lock()
        self._attached = set()          # live tenant ids (daemon side)
        self._seen_tenants = set()
        self._published = {}            # stream id -> set of published seqs
        self._ended = set()             # stream ids past their END frame
        self._delivered = set()         # consumer side: seqs delivered here
        self._consumer_ended = False
        self.events_checked = 0

    def _fail(self, message):
        raise ProtocolViolation('[serve monitor: {}] {}'.format(self._name, message))

    # -- daemon-side events --------------------------------------------------

    def on_attach(self, tenant_id, stream_id):
        with self._lock:
            self.events_checked += 1
            if tenant_id in self._attached:
                self._fail('tenant {} attached twice'.format(tenant_id))
            self._attached.add(tenant_id)
            self._seen_tenants.add(tenant_id)

    def on_detach(self, tenant_id):
        with self._lock:
            self.events_checked += 1
            if tenant_id not in self._attached:
                self._fail('detach of tenant {} which is not attached — a '
                           'double detach would free another tenant\'s ring '
                           'slot'.format(tenant_id))
            self._attached.discard(tenant_id)

    def on_evict(self, tenant_id):
        with self._lock:
            self.events_checked += 1
            if tenant_id not in self._attached:
                self._fail('eviction of tenant {} which is not attached'
                           .format(tenant_id))
            # an evicted tenant stays 'attached' until its client detaches —
            # eviction only stops its cursor from constraining the producer

    def on_publish(self, stream_id, seq):
        with self._lock:
            self.events_checked += 1
            if stream_id in self._ended:
                self._fail('publish on stream {} after its END frame'
                           .format(stream_id))
            if seq is not None:
                seen = self._published.setdefault(stream_id, set())
                if seq in seen:
                    self._fail('stream {} published seq {} twice — one decode '
                               'must publish exactly once (retries may '
                               'reorder seqs, never duplicate them)'
                               .format(stream_id, seq))
                seen.add(seq)

    def on_end(self, stream_id):
        with self._lock:
            self.events_checked += 1
            if stream_id in self._ended:
                self._fail('stream {} ended twice'.format(stream_id))
            self._ended.add(stream_id)

    # -- consumer-side events ------------------------------------------------

    def on_deliver(self, seq):
        with self._lock:
            self.events_checked += 1
            if self._consumer_ended:
                self._fail('batch delivered after the stream END frame')
            if seq is not None:
                if seq in self._delivered:
                    self._fail('batch seq {} delivered twice — the ring '
                               'delivered a slot twice to this consumer'
                               .format(seq))
                self._delivered.add(seq)

    def on_consumer_end(self):
        with self._lock:
            self.events_checked += 1
            if self._consumer_ended:
                self._fail('stream END delivered twice to this consumer')
            self._consumer_ended = True


class ElasticMonitor(object):
    """Runtime conformance monitor for the elastic resharding protocol
    (``docs/parallelism.md`` "Elastic pod sharding"; spec in
    ``analysis/protocol/elastic_spec.py``). Each host checks its observable
    projection of the pod-wide protocol:

    * the generation number is strictly monotonic (``on_reshard``);
    * no row group is claimed after it was committed, and no row group is
      claimed while another host's un-expired lease pins it in flight;
    * no row group is committed twice, and every commit follows a claim by
      the committing host (a commit without a claim is the signature of a
      lease being honored after it was handed off);
    * a lease expiry releases the departed host's claims for adoption;
      a (re)join clears its expired status.

    Violations raise :class:`~petastorm_tpu.errors.ProtocolViolation`.
    """

    def __init__(self, name='elastic'):
        self._name = name
        self._lock = threading.Lock()
        self._generation = 0
        self._claims = {}       # item -> claiming host
        self._delivered = set()
        self._expired = set()
        self.events_checked = 0

    def _fail(self, message):
        raise ProtocolViolation('[elastic monitor: {}] {}'.format(self._name,
                                                                  message))

    def on_join(self, host):
        with self._lock:
            self.events_checked += 1
            self._expired.discard(host)

    def on_lease_expire(self, host):
        with self._lock:
            self.events_checked += 1
            self._expired.add(host)
            # the departed host's claims become adoptable exactly now
            for item, holder in list(self._claims.items()):
                if holder == host:
                    del self._claims[item]

    def on_reshard(self, generation, members=()):
        with self._lock:
            self.events_checked += 1
            if generation <= self._generation:
                self._fail('generation regressed: {} -> {} — shard maps '
                           'must advance monotonically or two hosts can '
                           'disagree about ownership forever'
                           .format(self._generation, generation))
            self._generation = generation

    def on_claim(self, host, item):
        with self._lock:
            self.events_checked += 1
            if item in self._delivered:
                self._fail('host {} claimed row group {!r} which was already '
                           'committed — re-reading it would deliver the '
                           'group twice'.format(host, item))
            holder = self._claims.get(item)
            if holder is not None and holder != host:
                self._fail('host {} claimed row group {!r} while host {} '
                           'still holds it under a live lease — in-flight '
                           'groups move only after lease expiry'
                           .format(host, item, holder))
            self._claims[item] = host

    def on_deliver(self, host, item):
        with self._lock:
            self.events_checked += 1
            if item in self._delivered:
                self._fail('row group {!r} committed twice (second commit by '
                           'host {})'.format(item, host))
            holder = self._claims.pop(item, None)
            if holder is None:
                self._fail('host {} committed row group {!r} without a live '
                           'claim — its lease was already handed off'
                           .format(host, item))
            if holder != host:
                self._fail('host {} committed row group {!r} claimed by host '
                           '{}'.format(host, item, holder))
            self._delivered.add(item)

    @property
    def snapshot(self):
        with self._lock:
            return {'generation': self._generation,
                    'claims': dict(self._claims),
                    'delivered': len(self._delivered),
                    'expired': sorted(self._expired),
                    'events_checked': self.events_checked}


class FabricMonitor(object):
    """Runtime conformance monitor for the chunk-fabric transfer protocol
    (``docs/fabric.md``; spec in ``analysis/protocol/fabric_spec.py``). Each
    fetching process checks its observable projection:

    * a request is only ever issued to a peer whose breaker admitted it
      (``on_request`` with ``allowed=False`` is the violation — a breaker
      that opened mid-flight on an already-issued request is NOT one, which
      is why the client reports the admission decision, not the later state);
    * bytes are only populated into the mirror after verification
      (``on_populate`` with ``verified=False``), and a chunk is populated at
      most once per process between invalidations (``on_invalidate`` is how
      an eviction legitimately re-opens a chunk for population);
    * every fetch resolves through exactly one of the spec's terminal
      outcomes: ``peer``, ``fallback``, or ``error`` (``on_outcome``).

    Violations raise :class:`~petastorm_tpu.errors.ProtocolViolation`.
    """

    _OUTCOMES = ('peer', 'fallback', 'error')

    def __init__(self, name='fabric'):
        self._name = name
        self._lock = threading.Lock()
        self._populated = set()     # digests currently mirrored (our view)
        self.events_checked = 0

    def _fail(self, message):
        raise ProtocolViolation('[fabric monitor: {}] {}'.format(self._name,
                                                                 message))

    def on_request(self, peer, allowed):
        with self._lock:
            self.events_checked += 1
            if not allowed:
                self._fail('request issued to peer {} whose circuit breaker '
                           'is open — an open breaker must shed load, not '
                           'shape it'.format(peer))

    def on_populate(self, digest, verified):
        with self._lock:
            self.events_checked += 1
            if not verified:
                self._fail('unverified bytes for chunk {} reached the mirror '
                           '— bytes that fail the content hash must be '
                           'discarded'.format(digest))
            if digest in self._populated:
                self._fail('chunk {} populated twice without an intervening '
                           'invalidation — population must be exactly-once '
                           'per host'.format(digest))
            self._populated.add(digest)

    def on_invalidate(self, digest):
        """The mirror for ``digest`` was evicted: population is legal again."""
        with self._lock:
            self.events_checked += 1
            self._populated.discard(digest)

    def on_outcome(self, key, outcome):
        with self._lock:
            self.events_checked += 1
            if outcome not in self._OUTCOMES:
                self._fail('fetch of {!r} resolved with unknown outcome {!r} '
                           '(must be one of {})'.format(key, outcome,
                                                        self._OUTCOMES))

    @property
    def snapshot(self):
        with self._lock:
            return {'populated': len(self._populated),
                    'events_checked': self.events_checked}


def fabric_monitor_from_env(explicit, name):
    """Resolve a fabric ``monitor`` argument exactly like
    :func:`monitor_from_env`, honoring ``PSTPU_FABRIC_MONITOR`` (with
    ``PSTPU_PROTOCOL_MONITOR`` as the umbrella opt-in)."""
    import os
    if explicit is None:
        env = os.environ.get('PSTPU_FABRIC_MONITOR',
                             os.environ.get('PSTPU_PROTOCOL_MONITOR', ''))
        explicit = env not in ('', '0')
    if not explicit:
        return None
    if isinstance(explicit, FabricMonitor):
        return explicit
    return FabricMonitor(name=name)


def elastic_monitor_from_env(explicit, name):
    """Resolve an elastic ``monitor`` argument exactly like
    :func:`monitor_from_env`, honoring ``PSTPU_ELASTIC_MONITOR`` (with
    ``PSTPU_PROTOCOL_MONITOR`` as the umbrella opt-in)."""
    import os
    if explicit is None:
        env = os.environ.get('PSTPU_ELASTIC_MONITOR',
                             os.environ.get('PSTPU_PROTOCOL_MONITOR', ''))
        explicit = env not in ('', '0')
    if not explicit:
        return None
    if isinstance(explicit, ElasticMonitor):
        return explicit
    return ElasticMonitor(name=name)


def serve_monitor_from_env(explicit, name):
    """Resolve a serve-side ``monitor`` argument exactly like
    :func:`monitor_from_env`, honoring ``PSTPU_SERVE_MONITOR`` (with
    ``PSTPU_PROTOCOL_MONITOR`` as the umbrella opt-in)."""
    import os
    if explicit is None:
        env = os.environ.get('PSTPU_SERVE_MONITOR',
                             os.environ.get('PSTPU_PROTOCOL_MONITOR', ''))
        explicit = env not in ('', '0')
    if not explicit:
        return None
    if isinstance(explicit, ServeMonitor):
        return explicit
    return ServeMonitor(name=name)


def monitor_from_env(explicit, name):
    """Resolve a pool's ``protocol_monitor`` constructor argument: a
    :class:`ProtocolMonitor` instance is used as-is, truthy builds a fresh
    one, ``None`` consults ``PSTPU_PROTOCOL_MONITOR`` (the process-wide
    opt-in used by the fault-tolerance suite and the bench ``--protocol-
    monitor`` flags), falsy disables."""
    import os
    if explicit is None:
        explicit = os.environ.get('PSTPU_PROTOCOL_MONITOR', '') not in ('', '0')
    if not explicit:
        return None
    if isinstance(explicit, ProtocolMonitor):
        return explicit
    return ProtocolMonitor(name=name)


__all__ = ['ElasticMonitor', 'FabricMonitor', 'ProtocolMonitor',
           'ProtocolViolation', 'ServeMonitor', 'elastic_monitor_from_env',
           'fabric_monitor_from_env', 'monitor_from_env',
           'serve_monitor_from_env']
