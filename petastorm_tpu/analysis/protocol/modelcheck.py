"""Small-scope exhaustive model checker for the supervision protocol spec.

BFS over every interleaving of :mod:`spec`'s transition system for a small
configuration, with canonical state hashing (worker-slot symmetry reduction)
and counterexample trace minimization. BFS order makes the first trace to any
violation minimal in length; :func:`minimize_trace` then greedily drops events
that are not needed to reproduce it.

CLI (``petastorm-tpu-modelcheck``)::

    petastorm-tpu-modelcheck                       # the default small scope
    petastorm-tpu-modelcheck --workers 3 --items 4 --crashes 2
    petastorm-tpu-modelcheck --mutate requeue_same_id   # must find a trace

Exit codes: 0 = exhausted, all invariants hold; 1 = violation found (the
minimized trace is printed); 2 = usage error; 3 = budget exhausted before the
state space was (the verdict is then only as good as the explored prefix).

The tier-1 test (``tests/test_protocol.py``) runs the default scope with an
explicit wall-clock budget AND a state-count floor
(:data:`DEFAULT_STATE_FLOOR`), so the exhaustive search cannot silently
degenerate into checking a trivial space.
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
import time

from petastorm_tpu.analysis.protocol import spec as S

#: the default small scope: >= 3 workers, >= 4 items, >= 2 injected crashes
DEFAULT_SCOPE = dict(workers=3, items=4, crashes=2, retries=1, errors=0,
                     policy='skip', publish=True)

#: the default scope must explore at least this many canonical states — a
#: regression tripwire against accidental transition pruning (the real count
#: sits well above; see tests/test_protocol.py)
DEFAULT_STATE_FLOOR = 500_000

#: a second, error-heavy scope exercising the retry/quarantine lattice the
#: crash-only default cannot reach
ERROR_SCOPE = dict(workers=2, items=2, crashes=1, retries=1, errors=2,
                   policy='skip', publish=True)


class CheckResult(object):
    """Outcome of one model-checking run."""

    __slots__ = ('config', 'exhausted', 'states', 'transitions', 'depth',
                 'elapsed_s', 'violation', 'trace', 'terminal_states')

    def __init__(self, config):
        self.config = config
        self.exhausted = False
        self.states = 0
        self.transitions = 0
        self.depth = 0
        self.elapsed_s = 0.0
        self.violation = None   # invariant name, or None
        self.trace = None       # minimized label sequence, or None
        self.terminal_states = 0

    @property
    def ok(self):
        return self.exhausted and self.violation is None

    def to_dict(self):
        return {'config': self.config.describe(), 'exhausted': self.exhausted,
                'states': self.states, 'transitions': self.transitions,
                'depth': self.depth, 'elapsed_s': round(self.elapsed_s, 3),
                'terminal_states': self.terminal_states,
                'violation': self.violation,
                'trace': [format_label(l) for l in self.trace] if self.trace else None}


def check(cfg, budget_s=None, max_states=None):
    """Exhaustively explore ``cfg``'s state space breadth-first.

    Stops at the first invariant violation (returning its minimized trace), at
    ``budget_s`` wall seconds / ``max_states`` states (``exhausted=False``), or
    when the frontier empties (``exhausted=True``).
    """
    result = CheckResult(cfg)
    t0 = time.monotonic()
    init = S.canonicalize(S.initial_state(cfg), cfg)
    parents = {init: None}  # canonical state -> (parent_state, label) | None
    frontier = collections.deque([(init, 0)])
    result.states = 1

    violation = S.check_state(init, cfg)
    violating = init if violation else None
    popped = 0
    while frontier and violation is None:
        state, depth = frontier.popleft()
        popped += 1
        result.depth = max(result.depth, depth)
        succ = S.successors(state, cfg)
        result.transitions += len(succ)
        if not succ:
            result.terminal_states += 1
            violation = S.check_terminal(state, cfg)
            if violation:
                violating = state
                break
        for label, ns in succ:
            if ns in parents:
                continue
            parents[ns] = (state, label)
            result.states += 1
            v = S.check_state(ns, cfg)
            if v is not None:
                violation, violating = v, ns
                break
            frontier.append((ns, depth + 1))
        if violation is None and popped % 2048 == 0:
            # budget checks keyed on POPPED states: a long all-duplicates
            # stretch must still honor the wall clock
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                break
            if max_states is not None and result.states >= max_states:
                break
    else:
        if violation is None:
            result.exhausted = True

    result.elapsed_s = time.monotonic() - t0
    if violation is not None:
        result.violation = violation
        trace = _reconstruct(parents, violating)
        result.trace = minimize_trace(cfg, trace, violation)
    return result


def _reconstruct(parents, state):
    trace = []
    while parents[state] is not None:
        state, label = parents[state]
        trace.append(label)
    trace.reverse()
    return trace


def _trace_violates(cfg, trace, violation):
    """Does ``trace`` replay to a state exhibiting ``violation``? Safety
    violations are checked on every prefix state; the termination violation on
    the final state (which must also be quiescent)."""
    state = S.canonicalize(S.initial_state(cfg), cfg)
    for label in trace:
        state = S.apply_label(state, cfg, label)
        if state is None:
            return False
        if S.check_state(state, cfg) == violation:
            return True
    if violation == 'epoch_termination':
        return (not S.successors(state, cfg)
                and S.check_terminal(state, cfg) == violation)
    return False


def minimize_trace(cfg, trace, violation):
    """Greedy delta-minimization: drop any event whose removal leaves a valid
    trace still exhibiting ``violation``. BFS traces are already length-minimal
    to their particular state; this additionally strips steps that only padded
    the path (e.g. unrelated workers' progress)."""
    trace = list(trace)
    changed = True
    while changed:
        changed = False
        i = 0
        while i < len(trace):
            candidate = trace[:i] + trace[i + 1:]
            if _trace_violates(cfg, candidate, violation):
                trace = candidate
                changed = True
            else:
                i += 1
    return trace


def random_walk(cfg, seed, max_steps=500):
    """One seeded random schedule through the spec, over RAW (non-canonical)
    successors so dispatch ids and slot indices stay globally stable — the
    form :func:`spec.replay_into_monitor` needs. Returns ``(trace,
    final_state)``; used by the randomized-schedule conformance tests."""
    import random
    rng = random.Random(seed)
    state = S.initial_state(cfg)
    trace = []
    for _ in range(max_steps):
        succ = S.successors(state, cfg, canonical=False)
        if not succ:
            break
        label, state = succ[rng.randrange(len(succ))]
        trace.append(label)
    return trace, state


def format_label(label):
    """One human-readable line per transition, for counterexample printing."""
    kind = label[0]
    if kind == 'dispatch':
        return 'dispatch item={} as d={} -> worker {}'.format(label[2], label[1], label[3])
    if kind == 'pickup':
        return 'worker {} picks up d={} (claim enqueued)'.format(label[1], label[2])
    if kind == 'publish':
        return 'worker {} publishes payload for d={}'.format(label[1], label[2])
    if kind == 'worker_done':
        return 'worker {} sends done for d={}'.format(label[1], label[2])
    if kind == 'worker_error':
        return 'worker {} sends error for d={}'.format(label[1], label[2])
    if kind == 'crash':
        return 'worker {} CRASHES (pipe lost, channel survives)'.format(label[1])
    if kind == 'finish_death':
        return 'supervisor finishes worker {} death (orphan={})'.format(label[1], label[2])
    if kind == 'sweep':
        parts = ('{} d={}{}'.format(a, d, ' -> d={} w{}'.format(nd, w) if a == 'requeue' else '')
                 for a, d, nd, w in label[1])
        return 'quiet-window sweep: ' + ', '.join(parts)
    if kind.startswith('consume_'):
        rest = kind[len('consume_'):]
        extra = ''
        if rest == 'data':
            extra = ' (live)' if label[3] else ' (stale, dropped)'
        elif rest == 'error_requeue':
            extra = ' -> requeued as d={} to worker {}'.format(label[3], label[4])
        return 'consumer pops {} for d={} from worker {}{}'.format(
            rest.split('_')[0] if rest not in ('claim',) else 'claim',
            label[2], label[1], extra)
    if kind.startswith('orphan_'):
        rest = kind[len('orphan_'):]
        if rest == 'requeue':
            return 'orphan d={} requeued as d={} to worker {}'.format(
                label[1], label[2], label[3])
        return 'orphan d={}: {}'.format(label[1], rest)
    return repr(label)


def format_trace(result):
    lines = ['counterexample ({} steps, invariant: {}):'.format(
        len(result.trace), result.violation)]
    lines.extend('  {:>3}. {}'.format(i + 1, format_label(label))
                 for i, label in enumerate(result.trace))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-modelcheck',
        description='Exhaustive small-scope model checker for the worker-pool '
                    'supervision protocol (docs/protocol.md). Exit codes: 0 '
                    'exhausted+clean, 1 violation (minimized trace printed), '
                    '2 usage error, 3 budget ran out before exhaustion.')
    parser.add_argument('--serve', action='store_true',
                        help='check the serve fan-out protocol '
                             '(multi-consumer broadcast-ring invariants, '
                             'docs/serve.md) instead of the supervision '
                             'protocol; --mutate then takes a serve mutation')
    parser.add_argument('--elastic', action='store_true',
                        help='check the elastic resharding protocol (pod '
                             'host join/leave mid-epoch, exactly-once '
                             'handoff; docs/parallelism.md) instead of the '
                             'supervision protocol; --mutate then takes an '
                             'elastic mutation')
    parser.add_argument('--fabric', action='store_true',
                        help='check the chunk-fabric transfer protocol '
                             '(peer-first fetch, circuit breakers, verified '
                             'population, guaranteed fallback; '
                             'docs/fabric.md) instead of the supervision '
                             'protocol; --mutate then takes a fabric '
                             'mutation')
    parser.add_argument('--workers', type=int, default=DEFAULT_SCOPE['workers'])
    parser.add_argument('--items', type=int, default=DEFAULT_SCOPE['items'])
    parser.add_argument('--crashes', type=int, default=DEFAULT_SCOPE['crashes'])
    parser.add_argument('--retries', type=int, default=DEFAULT_SCOPE['retries'])
    parser.add_argument('--errors', type=int, default=DEFAULT_SCOPE['errors'])
    parser.add_argument('--policy', choices=('raise', 'skip', 'retry'),
                        default=DEFAULT_SCOPE['policy'])
    parser.add_argument('--no-publish', action='store_true',
                        help='do not model the payload message as a separate '
                             'step (smaller space, weaker delivery invariant)')
    from petastorm_tpu.analysis.protocol import elastic_spec as EL
    from petastorm_tpu.analysis.protocol import fabric_spec as FB
    from petastorm_tpu.analysis.protocol import serve_spec as SV
    parser.add_argument('--mutate',
                        choices=S.MUTATIONS + SV.MUTATIONS + EL.MUTATIONS
                        + FB.MUTATIONS,
                        default=None,
                        help='seed one protocol defect; the checker must then '
                             'produce a counterexample')
    parser.add_argument('--budget-s', type=float, default=600.0,
                        help='wall-clock exploration budget (default 600)')
    parser.add_argument('--max-states', type=int, default=None)
    parser.add_argument('--min-states', type=int, default=None,
                        help='fail (exit 3) when exhaustion explored fewer '
                             'canonical states than this floor')
    parser.add_argument('--json', action='store_true')
    try:
        args = parser.parse_args(argv)
        if sum((args.serve, args.elastic, args.fabric)) > 1:
            raise ValueError('--serve, --elastic, and --fabric are mutually '
                             'exclusive')
        if args.serve:
            cfg = SV.ServeSpecConfig(mutation=args.mutate,
                                     **SV.DEFAULT_SERVE_SCOPE)
        elif args.elastic:
            cfg = EL.ElasticSpecConfig(mutation=args.mutate,
                                       **EL.DEFAULT_ELASTIC_SCOPE)
        elif args.fabric:
            cfg = FB.FabricSpecConfig(mutation=args.mutate,
                                      **FB.DEFAULT_FABRIC_SCOPE)
        else:
            cfg = S.SpecConfig(workers=args.workers, items=args.items,
                               crashes=args.crashes, retries=args.retries,
                               errors=args.errors, policy=args.policy,
                               publish=not args.no_publish, mutation=args.mutate)
    except (SystemExit, ValueError) as e:
        if isinstance(e, SystemExit):
            return 2 if e.code else 0
        print('error: {}'.format(e), file=sys.stderr)
        return 2

    if args.serve or args.elastic or args.fabric:
        module = SV if args.serve else (EL if args.elastic else FB)
        result = module.check(cfg, budget_s=args.budget_s,
                              max_states=args.max_states)
        plane = 'serve' if args.serve else ('elastic' if args.elastic
                                            else 'fabric')
        if args.json:
            print(json.dumps(result.to_dict(), indent=2))
        else:
            print('{} scope: {}'.format(plane, cfg.describe()))
            print('explored {} canonical states, {} transitions, depth {}, '
                  '{} terminal, in {:.2f}s'.format(
                      result.states, result.transitions, result.depth,
                      result.terminal_states, result.elapsed_s))
            if result.violation:
                print('counterexample ({} steps, invariant: {}):'.format(
                    len(result.trace), result.violation))
                for i, label in enumerate(result.trace):
                    print('  {:>3}. {!r}'.format(i + 1, label))
            elif result.exhausted:
                print('exhausted: all invariants hold ({})'.format(
                    ', '.join(module.INVARIANTS)))
            else:
                print('NOT exhausted: budget ran out — verdict covers only '
                      'the explored prefix')
        if result.violation:
            return 1
        if not result.exhausted:
            return 3
        if args.min_states is not None and result.states < args.min_states:
            print('state count {} below the declared floor {}'.format(
                result.states, args.min_states), file=sys.stderr)
            return 3
        return 0

    result = check(cfg, budget_s=args.budget_s, max_states=args.max_states)
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
    else:
        print('scope: {}'.format(cfg.describe()))
        print('explored {} canonical states, {} transitions, depth {}, '
              '{} terminal, in {:.2f}s'.format(
                  result.states, result.transitions, result.depth,
                  result.terminal_states, result.elapsed_s))
        if result.violation:
            print(format_trace(result))
        elif result.exhausted:
            print('exhausted: all invariants hold ({})'.format(', '.join(S.INVARIANTS)))
        else:
            print('NOT exhausted: budget ran out — verdict covers only the '
                  'explored prefix')
    if result.violation:
        return 1
    if not result.exhausted:
        return 3
    if args.min_states is not None and result.states < args.min_states:
        print('state count {} below the declared floor {} — the search '
              'degenerated'.format(result.states, args.min_states), file=sys.stderr)
        return 3
    return 0


if __name__ == '__main__':
    sys.exit(main())
