"""Executable spec of the worker-pool supervision protocol.

The protocol that keeps ``ProcessPool`` exactly-once under crashes (dispatch-id
ownership, claim heartbeats, two-stage death handling, stale-straggler
dropping, quiet-window sweep — ``docs/robustness.md``) is stated here as an
explicit-state transition system small enough to check exhaustively
(``modelcheck.py``) and deterministic enough to check the real implementation
against at runtime (``monitor.py``). ``docs/protocol.md`` is the prose
companion: state vocabulary, transition catalog, invariant catalog, and how to
read a counterexample trace.

Model scope (what is abstracted):

* **Time is abstracted to structure.** Grace windows, heartbeat staleness and
  the quiet-window timer become *enablement conditions*: the sweep may fire
  whenever the supervisor-visible gates hold (all live workers idle, channels
  silent, retired channels drained). The model therefore includes schedules
  the timers make merely unlikely — e.g. a sweep firing while an item still
  sits in a live worker's dispatch pipe — which is exactly why the stale-drop
  rules must carry the exactly-once invariant on their own.
* **Channels are FIFO**, matching the shm ring; the zmq fallback's
  grace-period drain approximates the ring's exact "retired channel empty"
  test and is modeled by the latter.
* Respawn always succeeds (slot shedding / ``WorkerPoolDepletedError`` is a
  degraded-mode concern, not a protocol-invariant concern); serialization,
  blob routing and telemetry piggybacks are payload concerns with no
  accounting effect and are not modeled (``metrics``/idle ``heartbeat``
  messages never change supervisor ownership state).

Two sound reductions keep the small-scope search exhaustible:

* **Symmetry canonicalization.** Worker slots are interchangeable, so states
  are canonicalized by sorting the per-slot component; logical items are
  interchangeable too (identity enters the dynamics only through the per-item
  accounting vectors and in-flight records), so dispatched items are
  canonically renamed by their accounting signature. Dispatch ids enter the
  dynamics only through equality and fresh allocation, so they are densely
  renumbered order-preservingly (a bisimulation quotient) — except for
  mutated specs, whose counterexample traces must keep globally stable ids
  for :func:`replay_into_monitor`.
* **Bounded transports.** The real results channel is a fixed-capacity ring
  and the dispatch pipe has a zmq HWM — workers block, they do not buffer
  unboundedly. The model mirrors that with small caps
  (``SpecConfig(chan_cap=..., pipe_cap=...)``): a send into a full channel is
  simply not enabled until the consumer drains. Exhaustiveness is relative to
  these caps, as is standard for small-scope checking.
* **Partial-order reduction.** Popping a claim, a payload, a completion, or a
  stale error off a channel head is executed *eagerly* as the state's only
  explored transition: each such pop stays enabled until taken (nothing else
  removes a channel head), commutes with every other enabled transition
  (channel appends land at the tail; a crash preserves the channel; the
  ``finish_death``/``sweep`` gates that read the claim table are necessarily
  disabled while the relevant channel is non-empty — the claim a pop might
  clear belongs to the worker whose channel holds the message), and affects
  the invariant predicates only monotonically — so every violation reachable
  by delaying the pop is reachable (same canonical state) by taking it first.
  Branching remains exactly where protocol decisions live: dispatch/requeue
  routing, worker-step-vs-crash interleavings, live error handling, orphan
  resolution and the sweep. Once the crash and error budgets are exhausted, a
  worker's only-move steps (pickup; the published worker's completion send)
  join the eager set by the same argument — their lone conflict partners were
  the crash of the same worker and the quiet-window sweep, the latter provably
  never co-enabled with an accounted claim. The reduction is disabled for
  mutated specs: a mutation (e.g. ``requeue_same_id``) may break the
  unique-dispatch-id assumption several of the commutation arguments rest on.

Mutations (``SpecConfig(mutation=...)``) re-introduce one protocol defect
each, so the checker's teeth can be tested: every mutation must yield a
counterexample trace (see ``tests/test_protocol.py``).
"""

from __future__ import annotations

import itertools

from petastorm_tpu.errors import ProtocolViolation

# worker phases
IDLE, WORK, PUB = 0, 1, 2

# results-channel message kinds, named after protocol.MESSAGE_KINDS values
C_CLAIM, C_DATA, C_DONE, C_ERROR = 'claim', 'data', 'done', 'error'

# state tuple indices
(NEXT_ITEM, NEXT_D, INFLIGHT, SLOTS, ORPHANS, DELIVERED, COMPLETED,
 QUARANTINED, COMPLETED_ITEMS, CRASHES, ERRORS, DEATHS_SEEN, RAISED) = range(13)

# slot tuple indices: (alive, phase, cur, pipe, chan, sup_busy)
S_ALIVE, S_PHASE, S_CUR, S_PIPE, S_CHAN, S_SUP = range(6)

#: the five checked invariants, in catalog order (docs/protocol.md)
INVARIANTS = (
    'exactly_once_delivery',      # every item's payload reaches the consumer <= once
    'exactly_once_completion',    # every item completes (delivered/quarantined/raised) <= once
    'no_double_count',            # pool completed_items == sum of per-item completions
    'bounded_attempts',           # no item exceeds max_item_retries failed attempts
    'epoch_termination',          # every quiescent run converges: all items resolved
)

#: seedable spec defects for verifying the checker/monitor have teeth
MUTATIONS = (
    'requeue_same_id',          # requeue reuses the old dispatch id (stale detection dies)
    'requeue_published',        # error-requeue ignores the published flag (double delivery)
    'no_stale_drop',            # stale _DONE counted as a completion (double count)
    'no_drain_before_respawn',  # ownership decided before the dead worker's channel drains
)


class SpecConfig(object):
    """Small-scope configuration of the transition system.

    :param workers: pool slots (symmetric; canonicalization exploits this)
    :param items: logical items the ventilator will dispatch
    :param crashes: worker-crash budget (SIGKILL at any point)
    :param retries: ``max_item_retries`` — failed attempts allowed per item
    :param errors: worker-raised error budget (0 = crash-only exploration)
    :param policy: ``'raise' | 'skip' | 'retry'`` — the ErrorPolicy under test
    :param publish: model the payload (``data``) message as a separate step, so
        crash/error-after-publish interleavings exist (required for the
        delivery invariant to mean anything)
    :param mutation: one of :data:`MUTATIONS` (None = the real protocol)
    :param chan_cap: results-channel capacity in messages (the shm ring bound)
    :param pipe_cap: dispatch-pipe capacity for fresh dispatches (the zmq HWM
        bound; requeues bypass it — the implementation's sender buffers them)
    """

    __slots__ = ('workers', 'items', 'crashes', 'retries', 'errors', 'policy',
                 'publish', 'mutation', 'chan_cap', 'pipe_cap')

    def __init__(self, workers=3, items=4, crashes=2, retries=1, errors=0,
                 policy='skip', publish=True, mutation=None,
                 chan_cap=3, pipe_cap=1):
        if workers < 1 or items < 0 or crashes < 0 or retries < 0 or errors < 0:
            raise ValueError('negative/empty scope parameter')
        if crashes >= workers:
            # all slots may then be dead at once with an undeliverable requeue
            # in hand; the implementation's zmq PUSH would simply buffer until
            # a respawn connects, which this model does not represent
            raise ValueError('crashes budget must stay below workers '
                             '(got {} >= {})'.format(crashes, workers))
        if policy not in ('raise', 'skip', 'retry'):
            raise ValueError('policy must be raise/skip/retry, got {!r}'.format(policy))
        if mutation is not None and mutation not in MUTATIONS:
            raise ValueError('unknown mutation {!r} (expected one of {})'.format(
                mutation, MUTATIONS))
        if chan_cap < 3 or pipe_cap < 1:
            # a channel must at least hold one item's claim+data+done burst
            raise ValueError('chan_cap must be >= 3 and pipe_cap >= 1')
        self.workers, self.items, self.crashes = workers, items, crashes
        self.retries, self.errors, self.policy = retries, errors, policy
        self.publish, self.mutation = bool(publish), mutation
        self.chan_cap, self.pipe_cap = chan_cap, pipe_cap

    def describe(self):
        return ('workers={} items={} crashes={} retries={} errors={} policy={} '
                'publish={} chan_cap={} pipe_cap={}{}'.format(
                    self.workers, self.items, self.crashes, self.retries,
                    self.errors, self.policy, self.publish, self.chan_cap,
                    self.pipe_cap,
                    ' mutation={}'.format(self.mutation) if self.mutation else ''))


def initial_state(cfg):
    slot = (1, IDLE, -1, (), (), -1)
    return (0, 0, (), (slot,) * cfg.workers, (), (0,) * cfg.items,
            (0,) * cfg.items, (0,) * cfg.items, 0, 0, 0, 0, 0)


def _renumber_ids(state):
    """Order-preserving dense renumbering of the dispatch ids alive in
    ``state`` (ids only enter the dynamics through equality and fresh
    allocation, so this is a bisimulation quotient): two states whose requeue
    histories burned different id counts collapse. Skipped for mutated specs
    so counterexample traces keep globally stable ids — that stability is what
    :func:`replay_into_monitor` exercises."""
    ids = {rec[0] for rec in state[INFLIGHT]}
    ids.update(state[ORPHANS])
    for s in state[SLOTS]:
        if s[S_CUR] != -1:
            ids.add(s[S_CUR])
        if s[S_SUP] != -1:
            ids.add(s[S_SUP])
        ids.update(s[S_PIPE])
        ids.update(d for _k, d in s[S_CHAN])
    k = len(ids)
    if not ids:
        return state if state[NEXT_D] == 0 else _set(state, NEXT_D, 0)
    if max(ids) == k - 1:  # already dense: at most the allocator needs resetting
        return state if state[NEXT_D] == k else _set(state, NEXT_D, k)
    rn = {d: i for i, d in enumerate(sorted(ids))}
    rn[-1] = -1
    state = _set(state, NEXT_D, k)
    state = _set(state, INFLIGHT, tuple(sorted(
        (rn[d], it, att, pub) for d, it, att, pub in state[INFLIGHT])))
    state = _set(state, ORPHANS, tuple(sorted(rn[d] for d in state[ORPHANS])))
    slots = tuple(
        (s[S_ALIVE], s[S_PHASE], rn[s[S_CUR]],
         tuple(rn[d] for d in s[S_PIPE]),
         tuple((k, rn[d]) for k, d in s[S_CHAN]), rn[s[S_SUP]])
        for s in state[SLOTS])
    return _set(state, SLOTS, slots)


def canonicalize(state, cfg=None):
    """Collapse the spec symmetries to one representative: densely renumber
    dispatch ids (unmutated specs only), sort the interchangeable worker
    slots, then canonically rename the dispatched items by their accounting
    signature (two items with identical delivered/completed/quarantined
    counts and identical in-flight records are interchangeable)."""
    if cfg is None or cfg.mutation is None:
        state = _renumber_ids(state)
    state = state[:SLOTS] + (tuple(sorted(state[SLOTS])),) + state[SLOTS + 1:]
    ni = state[NEXT_ITEM]
    if ni <= 1:
        return state
    inflight = state[INFLIGHT]
    deliv, comp, quar = state[DELIVERED], state[COMPLETED], state[QUARANTINED]

    def sig(i):
        return (comp[i], deliv[i], quar[i],
                tuple((d, att, pub) for d, it, att, pub in inflight if it == i))

    order = sorted(range(ni), key=sig)
    if order == list(range(ni)):
        return state
    rename = {old: new for new, old in enumerate(order)}
    inflight = tuple(sorted((d, rename[it], att, pub)
                            for d, it, att, pub in inflight))

    def permute(vec):
        return tuple(vec[order[j]] for j in range(ni)) + vec[ni:]

    state = _set(state, INFLIGHT, inflight)
    state = _set(state, DELIVERED, permute(deliv))
    state = _set(state, COMPLETED, permute(comp))
    return _set(state, QUARANTINED, permute(quar))


def _set(t, i, v):
    return t[:i] + (v,) + t[i + 1:]


def _set_slot(state, w, slot):
    return _set(state, SLOTS, _set(state[SLOTS], w, slot))


def _bump(vec, i):
    return _set(vec, i, vec[i] + 1)


def _infl_get(inflight, d):
    for rec in inflight:
        if rec[0] == d:
            return rec
    return None


def _infl_del(inflight, d):
    return tuple(r for r in inflight if r[0] != d)


def _infl_add(inflight, rec):
    return tuple(sorted(inflight + (rec,)))


def _clear_claim(slots, d):
    """Mirror of ``ProcessPool._clear_claim``: a done/error for dispatch ``d``
    releases whichever supervisor-side ownership record names it."""
    out = list(slots)
    for w, s in enumerate(out):
        if s[S_SUP] == d:
            out[w] = _set(s, S_SUP, -1)
    return tuple(out)


def _complete(state, d, item):
    """Exactly-once completion accounting: remove from inflight, count the
    item complete, advance the pool counter."""
    state = _set(state, INFLIGHT, _infl_del(state[INFLIGHT], d))
    state = _set(state, COMPLETED, _bump(state[COMPLETED], item))
    return _set(state, COMPLETED_ITEMS, state[COMPLETED_ITEMS] + 1)


def _quarantine(state, d, item):
    state = _complete(state, d, item)
    return _set(state, QUARANTINED, _bump(state[QUARANTINED], item))


def _requeue(state, cfg, d, rec, target_w):
    """Re-dispatch ``rec`` under a NEW dispatch id routed to ``target_w``'s
    pipe (the ``requeue_same_id`` mutation keeps the old id — the defect the
    exactly-once argument hinges on never having)."""
    item, att = rec[1], rec[2]
    if cfg.mutation == 'requeue_same_id':
        nd = d
        inflight = _infl_add(_infl_del(state[INFLIGHT], d), (nd, item, att + 1, 0))
    else:
        nd = state[NEXT_D]
        state = _set(state, NEXT_D, nd + 1)
        inflight = _infl_add(_infl_del(state[INFLIGHT], d), (nd, item, att + 1, 0))
    state = _set(state, INFLIGHT, inflight)
    slot = state[SLOTS][target_w]
    state = _set_slot(state, target_w, _set(slot, S_PIPE, slot[S_PIPE] + (nd,)))
    return nd, state


def _fail_item(state, cfg, d, rec, live_workers, prefix):
    """The crash-failure policy of ``_fail_crashed_item``: retry within
    budget, else quarantine (skip) or poison-raise. Yields (label, state) per
    routing choice."""
    item, att = rec[1], rec[2]
    out = []
    if att < cfg.retries:
        for w in live_workers:
            nd, ns = _requeue(state, cfg, d, rec, w)
            out.append(((prefix + '_requeue', d, nd, w), ns))
    elif cfg.policy == 'skip':
        out.append(((prefix + '_quarantine', d), _quarantine(state, d, item)))
    else:
        ns = _set(_complete(state, d, item), RAISED, 1)
        out.append(((prefix + '_poison_raise', d), ns))
    return out


def _consume_head(state, cfg, w):
    """Transitions for the consumer popping the head of slot ``w``'s results
    channel: claims update the supervisor ownership view, data/done/error are
    classified live vs stale against the in-flight table — the stale-straggler
    drop that exactly-once rests on."""
    out = []
    s = state[SLOTS][w]
    kind, d = s[S_CHAN][0]
    popped = _set_slot(state, w, _set(s, S_CHAN, s[S_CHAN][1:]))
    rec = _infl_get(state[INFLIGHT], d)
    if kind == C_CLAIM:
        # _note_heartbeat: the supervisor view takes the claim verbatim,
        # stale or not
        ps = popped[SLOTS][w]
        ns = _set_slot(popped, w, _set(ps, S_SUP, d))
        out.append((('consume_claim', w, d), ns))
    elif kind == C_DATA:
        if rec is not None:
            ns = _set(popped, INFLIGHT,
                      _infl_add(_infl_del(popped[INFLIGHT], d),
                                (d, rec[1], rec[2], 1)))
            ns = _set(ns, DELIVERED, _bump(ns[DELIVERED], rec[1]))
            out.append((('consume_data', w, d, True), ns))
        else:
            out.append((('consume_data', w, d, False), popped))
    elif kind == C_DONE:
        ns = _set(popped, SLOTS, _clear_claim(popped[SLOTS], d))
        if rec is not None:
            out.append((('consume_done_live', w, d), _complete(ns, d, rec[1])))
        elif cfg.mutation == 'no_stale_drop':
            ns = _set(ns, COMPLETED_ITEMS, ns[COMPLETED_ITEMS] + 1)
            out.append((('consume_done_stale_counted', w, d), ns))
        else:
            out.append((('consume_done_stale', w, d), ns))
    else:  # C_ERROR
        ns = _set(popped, SLOTS, _clear_claim(popped[SLOTS], d))
        if rec is None:
            out.append((('consume_error_stale', w, d), ns))
        else:
            item, att, pub = rec[1], rec[2], rec[3]
            if pub and cfg.policy != 'raise' and cfg.mutation != 'requeue_published':
                # the item's payload already reached the consumer (FIFO:
                # its data message preceded this error) — re-running would
                # deliver twice, so it completes delivered instead
                out.append((('consume_error_published_complete', w, d),
                            _complete(ns, d, item)))
            elif att < cfg.retries and cfg.policy in ('skip', 'retry'):
                nlive = [x for x, sl in enumerate(ns[SLOTS]) if sl[S_ALIVE]]
                for tw in nlive:
                    nd, rs = _requeue(ns, cfg, d, rec, tw)
                    out.append((('consume_error_requeue', w, d, nd, tw), rs))
            elif cfg.policy == 'skip':
                out.append((('consume_error_quarantine', w, d),
                            _quarantine(ns, d, item)))
            else:
                rs = _set(_complete(ns, d, item), RAISED, 1)
                out.append((('consume_error_raise', w, d), rs))
    return out


def successors(state, cfg, canonical=True):
    """All enabled transitions of ``state`` as ``(label, next_state)`` pairs
    (canonicalized unless ``canonical=False`` — raw successors keep dispatch
    ids and slot indices globally stable, which random-walk traces replayed
    into the runtime monitor rely on). Labels are structured tuples (see
    ``replay_into_monitor`` for the mapping to runtime-monitor events)."""
    if state[RAISED]:
        return []
    out = []
    slots = state[SLOTS]
    inflight = state[INFLIGHT]
    live = [w for w, s in enumerate(slots) if s[S_ALIVE]]

    # partial-order reduction (module docstring): a channel head that is not a
    # LIVE error is popped eagerly as the sole explored transition — it
    # commutes with everything else enabled and only monotonically advances
    # the invariant predicates, so no violation is lost. Disabled for mutated
    # specs, whose broken id discipline voids the commutation argument.
    if cfg.mutation is None:
        for w, s in enumerate(slots):
            if s[S_CHAN]:
                kind, d = s[S_CHAN][0]
                if kind != C_ERROR or _infl_get(inflight, d) is None:
                    head = _consume_head(state, cfg, w)
                    if not canonical:
                        return head
                    return [(lab, canonicalize(ns, cfg)) for lab, ns in head]
        # once the crash/error budgets are spent, a worker's only-move steps
        # are safe singletons too (module docstring): pickup (unless a sweep
        # could race it) and the published worker's completion send
        if state[CRASHES] >= cfg.crashes and state[ERRORS] >= cfg.errors:
            sweep_possible = state[DEATHS_SEEN] and not state[ORPHANS] and inflight
            for w, s in enumerate(slots):
                if not s[S_ALIVE] or len(s[S_CHAN]) >= cfg.chan_cap:
                    continue
                if s[S_PHASE] == PUB:
                    d = s[S_CUR]
                    ns = _set_slot(state, w, (1, IDLE, -1, s[S_PIPE],
                                              s[S_CHAN] + ((C_DONE, d),), s[S_SUP]))
                    return [(('worker_done', w, d),
                             canonicalize(ns, cfg) if canonical else ns)]
                if s[S_PHASE] == IDLE and s[S_PIPE] and not sweep_possible:
                    d = s[S_PIPE][0]
                    ns = _set_slot(state, w, (1, WORK, d, s[S_PIPE][1:],
                                              s[S_CHAN] + ((C_CLAIM, d),), s[S_SUP]))
                    return [(('pickup', w, d),
                             canonicalize(ns, cfg) if canonical else ns)]

    # -- ventilator: dispatch the next item to a live worker's pipe ---------
    if state[NEXT_ITEM] < cfg.items:
        item = state[NEXT_ITEM]
        d = state[NEXT_D]
        base = _set(_set(state, NEXT_ITEM, item + 1), NEXT_D, d + 1)
        base = _set(base, INFLIGHT, _infl_add(inflight, (d, item, 0, 0)))
        for w in live:
            s = slots[w]
            if len(s[S_PIPE]) < cfg.pipe_cap:  # zmq HWM: full pipe blocks the sender
                ns = _set_slot(base, w, _set(s, S_PIPE, s[S_PIPE] + (d,)))
                out.append((('dispatch', d, item, w), ns))

    # -- worker-side steps --------------------------------------------------
    for w, s in enumerate(slots):
        if s[S_ALIVE]:
            # a full results channel blocks the sender (the ring's capacity
            # bound): the step simply is not enabled until the consumer drains
            chan_open = len(s[S_CHAN]) < cfg.chan_cap
            if s[S_PHASE] == IDLE and s[S_PIPE] and chan_open:
                d = s[S_PIPE][0]
                ns = _set_slot(state, w, (1, WORK, d, s[S_PIPE][1:],
                                          s[S_CHAN] + ((C_CLAIM, d),), s[S_SUP]))
                out.append((('pickup', w, d), ns))
            if s[S_PHASE] == WORK and chan_open:
                d = s[S_CUR]
                done = _set_slot(state, w, (1, IDLE, -1, s[S_PIPE],
                                            s[S_CHAN] + ((C_DONE, d),), s[S_SUP]))
                out.append((('worker_done', w, d), done))
                if cfg.publish:
                    pub = _set_slot(state, w, (1, PUB, d, s[S_PIPE],
                                               s[S_CHAN] + ((C_DATA, d),), s[S_SUP]))
                    out.append((('publish', w, d), pub))
                if state[ERRORS] < cfg.errors:
                    err = _set_slot(state, w, (1, IDLE, -1, s[S_PIPE],
                                               s[S_CHAN] + ((C_ERROR, d),), s[S_SUP]))
                    out.append((('worker_error', w, d),
                                _set(err, ERRORS, state[ERRORS] + 1)))
            elif s[S_PHASE] == PUB and chan_open:
                d = s[S_CUR]
                done = _set_slot(state, w, (1, IDLE, -1, s[S_PIPE],
                                            s[S_CHAN] + ((C_DONE, d),), s[S_SUP]))
                out.append((('worker_done', w, d), done))
                if state[ERRORS] < cfg.errors:
                    err = _set_slot(state, w, (1, IDLE, -1, s[S_PIPE],
                                               s[S_CHAN] + ((C_ERROR, d),), s[S_SUP]))
                    out.append((('worker_error', w, d),
                                _set(err, ERRORS, state[ERRORS] + 1)))
            if state[CRASHES] < cfg.crashes:
                # SIGKILL at any point: worker memory (phase, current item,
                # undelivered pipe) vanishes; committed channel messages
                # survive (shared memory outlives the writer)
                ns = _set_slot(state, w, (0, IDLE, -1, (), s[S_CHAN], s[S_SUP]))
                ns = _set(_set(ns, CRASHES, state[CRASHES] + 1), DEATHS_SEEN, 1)
                out.append((('crash', w), ns))
        else:
            drained = not s[S_CHAN]
            if drained or cfg.mutation == 'no_drain_before_respawn':
                # two-stage death handling: ownership + respawn only after the
                # dead worker's channel fully drained (the mutation breaks
                # exactly this and must lose an item)
                owned = s[S_SUP]
                ns = state
                if owned != -1:
                    ns = _set(ns, ORPHANS, tuple(sorted(set(ns[ORPHANS]) | {owned})))
                ns = _set_slot(ns, w, (1, IDLE, -1, (), s[S_CHAN] if not drained else (), -1))
                out.append((('finish_death', w, owned if owned != -1 else None), ns))

    # -- consumer: pop the head of any non-empty channel (FIFO per channel) -
    for w, s in enumerate(slots):
        if s[S_CHAN]:
            out.extend(_consume_head(state, cfg, w))

    # -- supervisor: orphan resolution --------------------------------------
    retired_drained = all(s[S_ALIVE] or not s[S_CHAN] for s in slots)
    if state[ORPHANS] and retired_drained:
        for d in state[ORPHANS]:
            base = _set(state, ORPHANS, tuple(x for x in state[ORPHANS] if x != d))
            rec = _infl_get(inflight, d)
            if rec is None:
                out.append((('orphan_noop', d), base))
            elif rec[3]:
                out.append((('orphan_complete_published', d),
                            _complete(base, d, rec[1])))
            else:
                out.extend(_fail_item(base, cfg, d, rec, live, 'orphan'))

    # -- supervisor: quiet-window sweep -------------------------------------
    if (state[DEATHS_SEEN] and not state[ORPHANS] and retired_drained and inflight
            and all((not s[S_ALIVE]) or (s[S_SUP] == -1 and not s[S_CHAN])
                    for s in slots)):
        # the supervisor cannot see live workers' dispatch pipes — the sweep
        # deliberately fires even when an item still sits in one (the model's
        # timers-as-structure over-approximation); exactly-once must survive
        # the resulting stale processing
        outcomes_per_item = []
        for rec in inflight:
            d, item, att, pub = rec
            if pub:
                outcomes_per_item.append([('complete', d, rec, None)])
            elif att < cfg.retries:
                outcomes_per_item.append([('requeue', d, rec, w) for w in live])
            elif cfg.policy == 'skip':
                outcomes_per_item.append([('quarantine', d, rec, None)])
            else:
                outcomes_per_item.append([('poison_raise', d, rec, None)])
        for combo in itertools.product(*outcomes_per_item):
            ns = state
            label_parts = []
            for action, d, rec, w in combo:
                if action == 'complete':
                    ns = _complete(ns, d, rec[1])
                    label_parts.append(('complete', d, None, None))
                elif action == 'requeue':
                    nd, ns = _requeue(ns, cfg, d, rec, w)
                    label_parts.append(('requeue', d, nd, w))
                elif action == 'quarantine':
                    ns = _quarantine(ns, d, rec[1])
                    label_parts.append(('quarantine', d, None, None))
                else:
                    ns = _set(_complete(ns, d, rec[1]), RAISED, 1)
                    label_parts.append(('poison_raise', d, None, None))
            out.append((('sweep', tuple(label_parts)), ns))

    if not canonical:
        return out
    return [(label, canonicalize(ns, cfg)) for label, ns in out]


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------

def check_state(state, cfg):
    """First violated safety invariant of ``state``, or None."""
    if any(v > 1 for v in state[DELIVERED]):
        return 'exactly_once_delivery'
    if any(v > 1 for v in state[COMPLETED]):
        return 'exactly_once_completion'
    if state[COMPLETED_ITEMS] != sum(state[COMPLETED]):
        return 'no_double_count'
    if any(v > 1 for v in state[QUARANTINED]) or \
            any(rec[2] > cfg.retries for rec in state[INFLIGHT]):
        return 'bounded_attempts'
    return None


def check_terminal(state, cfg):
    """'epoch_termination' when a quiescent (transition-free) non-raised state
    has unresolved items — a lost item or stuck accounting."""
    if state[RAISED]:
        return None  # the raise policy aborts the epoch by contract
    if sum(state[COMPLETED]) != cfg.items or state[INFLIGHT] or state[ORPHANS]:
        return 'epoch_termination'
    return None


# ---------------------------------------------------------------------------
# replay helpers (trace -> spec, trace -> runtime monitor)
# ---------------------------------------------------------------------------

def apply_label(state, cfg, label):
    """The successor of ``state`` reached by ``label``, or None when ``label``
    is not enabled — the validity test trace minimization is built on."""
    for lab, ns in successors(state, cfg):
        if lab == label:
            return ns
    return None


def replay_trace(cfg, trace):
    """Replay ``trace`` (a label sequence) from the initial state; returns the
    final state or raises :class:`ProtocolViolation` on an unenabled label."""
    state = canonicalize(initial_state(cfg), cfg)
    for i, label in enumerate(trace):
        ns = apply_label(state, cfg, label)
        if ns is None:
            raise ProtocolViolation(
                'trace step {} ({!r}) is not enabled in the spec'.format(i, label))
        state = ns
    return state


def events_for_label(label):
    """The runtime-monitor event calls the REAL pool would emit for one spec
    transition — ``(method_name, args...)`` tuples, consumed by
    :func:`replay_into_monitor`. Worker-internal steps (pickup, publish,
    crash...) emit nothing: the monitor, like the supervisor, only sees the
    consumer side."""
    kind = label[0]
    if kind == 'dispatch':
        return [('on_dispatch', label[1], label[2])]
    if kind == 'consume_claim':
        return [('on_message', 'claim', label[2], None)]
    if kind == 'consume_data':
        return [('on_message', 'data', label[2], label[3])]
    if kind == 'consume_done_live':
        return [('on_message', 'done', label[2], True),
                ('on_complete', label[2], True, False)]
    if kind == 'consume_done_stale':
        return [('on_message', 'done', label[2], False)]
    if kind == 'consume_done_stale_counted':
        # the no_stale_drop mutation: the pool books a stale done as live
        return [('on_message', 'done', label[2], True),
                ('on_complete', label[2], True, False)]
    if kind == 'consume_error_stale':
        return [('on_message', 'error', label[2], False)]
    if kind == 'consume_error_requeue':
        return [('on_message', 'error', label[2], True),
                ('on_requeue', label[2], label[3])]
    if kind == 'consume_error_quarantine':
        return [('on_message', 'error', label[2], True),
                ('on_complete', label[2], False, True)]
    if kind == 'consume_error_raise':
        return [('on_message', 'error', label[2], True),
                ('on_complete', label[2], False, False)]
    if kind == 'consume_error_published_complete':
        return [('on_message', 'error', label[2], True),
                ('on_complete', label[2], True, False)]
    if kind in ('orphan_requeue',):
        return [('on_requeue', label[1], label[2])]
    if kind == 'orphan_complete_published':
        return [('on_complete', label[1], True, False)]
    if kind == 'orphan_quarantine':
        return [('on_complete', label[1], False, True)]
    if kind == 'orphan_poison_raise':
        return [('on_complete', label[1], False, False)]
    if kind == 'sweep':
        events = []
        for action, d, nd, _w in label[1]:
            if action == 'complete':
                events.append(('on_complete', d, True, False))
            elif action == 'requeue':
                events.append(('on_requeue', d, nd))
            elif action == 'quarantine':
                events.append(('on_complete', d, False, True))
            else:
                events.append(('on_complete', d, False, False))
        return events
    return []  # worker-internal / noop steps: invisible to the consumer


def replay_into_monitor(trace, monitor):
    """Feed the consumer-visible projection of a spec trace through a runtime
    :class:`~petastorm_tpu.analysis.protocol.monitor.ProtocolMonitor`. Legal
    traces must be accepted; mutation counterexamples must raise
    :class:`ProtocolViolation` — the soundness/teeth contract tying the model
    checker and the monitor together."""
    for label in trace:
        for event in events_for_label(label):
            getattr(monitor, event[0])(*event[1:])


__all__ = [
    'INVARIANTS', 'MUTATIONS', 'ProtocolViolation', 'SpecConfig',
    'apply_label', 'canonicalize', 'check_state', 'check_terminal',
    'events_for_label', 'initial_state', 'replay_into_monitor', 'replay_trace',
    'successors',
]
