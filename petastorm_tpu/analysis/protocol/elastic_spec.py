"""Executable spec of the elastic resharding protocol (pod churn invariants).

``petastorm_tpu/elastic`` lets hosts join and leave mid-epoch: membership is
lease-based, row-group ownership is a pure function of ``(seed, epoch,
member set)`` stamped with a **generation** number, and in-flight row groups
follow dispatch-id ownership — a departed host's claimed-but-unfinished
groups move only after its lease expires, and a commit marker makes delivery
exclusive. This module states that design as an explicit-state transition
system small enough to check exhaustively, the same treatment PR 5 gave the
supervision protocol and PR 9 the serve fan-out.

Model scope:

* time is abstracted to structure: a lease expiry is a *transition* that is
  enabled once a host crashed (never before — that is exactly what the
  ``reassign_before_expiry`` mutation breaks);
* the shard map is abstracted to ``members[(item + generation) % len]`` —
  any deterministic function of (generation member set) exercises the same
  interleavings as the real rendezvous hash;
* a resharding is enabled whenever the alive set drifted from the current
  generation's member set; crashes and joins come from small budgets.

Checked invariants (catalog order; ``docs/protocol.md``):

* ``exactly_once_coverage`` — no row group is ever delivered twice
  (safety), and at quiescence none was marked done without a delivery;
* ``handoff_after_lease_expiry`` — no row group stays claimed by a host
  whose lease already expired;
* ``generation_monotonic`` — the generation number never regresses;
* ``epoch_termination`` — at quiescence with at least one surviving host,
  every row group has been delivered (join/leave cannot wedge the epoch).

Mutations re-introduce one defect each so the checker's teeth are testable:
``reassign_before_expiry`` (a live host's claims are released for adoption
before its lease expires — the classic double-read), ``skip_done_check``
(claims do not consult the commit scoreboard — re-delivery of finished
groups), ``drop_on_expire`` (a dead host's claims are marked done instead
of re-queued — silent data loss), ``generation_rollback`` (a resharding
reuses generation 0 — maps can regress and hosts disagree forever).
"""

from __future__ import annotations

import collections
import random
import time

# host statuses
OUT, ALIVE, CRASHED, GONE = 0, 1, 2, 3

#: the checked invariants, in catalog order (docs/protocol.md)
INVARIANTS = (
    'exactly_once_coverage',
    'handoff_after_lease_expiry',
    'generation_monotonic',
    'epoch_termination',
)

#: seedable spec defects proving the checker has teeth
MUTATIONS = (
    'reassign_before_expiry',
    'skip_done_check',
    'drop_on_expire',
    'generation_rollback',
)

# state tuple indices
GEN, GENSET, HOSTS, ITEMS, GHOSTS, FLAGS, CRASHES_LEFT, JOINS_LEFT = range(8)

# flags bitmask
F_GEN_REGRESS = 1

# item cell encoding, for cfg.hosts == H:
#   PEND (-1)      not yet claimed
#   h in [0, H)    claimed by host h, no delivery yet
#   H              done: delivered exactly once
#   H+1            done WITHOUT a delivery (mutant: dropped)
#   H+2            delivered twice (violation sink)
#   H+3+h          claimed by host h while a completed delivery already
#                  exists (mutant paths; delivering from here is a double)
PEND = -1


class ElasticSpecConfig(object):
    """Small-scope configuration.

    :param hosts: total host slots (identities 0..hosts-1)
    :param items: row groups in the epoch
    :param initial_alive: hosts alive (and in generation 1) at time zero
    :param crashes: crash-event budget over the run
    :param joins: join-event budget (hosts beyond the initial set)
    :param mutation: one of :data:`MUTATIONS`, or None for the real protocol
    """

    __slots__ = ('hosts', 'items', 'initial_alive', 'crashes', 'joins',
                 'mutation')

    def __init__(self, hosts=3, items=3, initial_alive=2, crashes=1, joins=1,
                 mutation=None):
        if hosts < 1 or items < 1 or initial_alive < 1:
            raise ValueError('empty scope parameter')
        if initial_alive > hosts:
            raise ValueError('initial_alive {} exceeds hosts {}'.format(
                initial_alive, hosts))
        if crashes < 0 or joins < 0:
            raise ValueError('negative event budget')
        if mutation is not None and mutation not in MUTATIONS:
            raise ValueError('unknown mutation {!r} (expected one of {})'.format(
                mutation, MUTATIONS))
        self.hosts = hosts
        self.items = items
        self.initial_alive = initial_alive
        self.crashes = crashes
        self.joins = joins
        self.mutation = mutation

    def describe(self):
        return ('hosts={} items={} initial_alive={} crashes={} joins={}{}'
                .format(self.hosts, self.items, self.initial_alive,
                        self.crashes, self.joins,
                        ' mutation={}'.format(self.mutation)
                        if self.mutation else ''))


def initial_state(cfg):
    hosts = tuple(ALIVE if h < cfg.initial_alive else OUT
                  for h in range(cfg.hosts))
    genset = tuple(range(cfg.initial_alive))
    return (1, genset, hosts, (PEND,) * cfg.items, (-1,) * cfg.items, 0,
            cfg.crashes, cfg.joins)


def canonicalize(state):
    """Hosts are NOT interchangeable (the shard map keys on identity), so
    canonical form is the state itself."""
    return state


def _owner(item, state):
    """The abstract shard map: deterministic in (generation, member set)."""
    genset = state[GENSET]
    return genset[(item + state[GEN]) % len(genset)]


def _done_value(cfg):
    return cfg.hosts


def _claim_value(cell, cfg):
    """The claiming host when ``cell`` is a claim, else None."""
    if 0 <= cell < cfg.hosts:
        return cell
    if cell >= cfg.hosts + 3:
        return cell - (cfg.hosts + 3)
    return None


def _set_item(state, i, value):
    items = state[ITEMS][:i] + (value,) + state[ITEMS][i + 1:]
    return state[:ITEMS] + (items,) + state[ITEMS + 1:]


def _set_ghost(state, i, value):
    ghosts = state[GHOSTS][:i] + (value,) + state[GHOSTS][i + 1:]
    return state[:GHOSTS] + (ghosts,) + state[GHOSTS + 1:]


def _set_host(state, h, status):
    hosts = state[HOSTS][:h] + (status,) + state[HOSTS][h + 1:]
    return state[:HOSTS] + (hosts,) + state[HOSTS + 1:]


def successors(state, cfg):
    """All enabled transitions as (label, canonical next state) pairs."""
    out = []
    H = cfg.hosts
    DONE, DROPPED, DOUBLE = H, H + 1, H + 2
    hosts = state[HOSTS]
    items = state[ITEMS]
    ghosts = state[GHOSTS]
    alive = tuple(h for h in range(H) if hosts[h] == ALIVE)

    for h in alive:
        in_gen = h in state[GENSET]
        for i, cell in enumerate(items):
            # claim: the current-generation owner takes a pending group
            if in_gen and cell == PEND and _owner(i, state) == h:
                out.append((('claim', h, i), _set_item(state, i, h)))
            # the skip_done_check defect: claims ignore the commit
            # scoreboard, so a finished group can be taken again
            if in_gen and cfg.mutation == 'skip_done_check' and cell == DONE \
                    and _owner(i, state) == h:
                out.append((('claim', h, i), _set_item(state, i, H + 3 + h)))
            # deliver: the claiming host finishes its in-flight group
            if cell == h:
                out.append((('deliver', h, i), _set_item(state, i, DONE)))
            if cell == H + 3 + h:
                out.append((('deliver', h, i), _set_item(state, i, DOUBLE)))
            # ghost delivery (reassign_before_expiry only): the host whose
            # claim was wrongly released still finishes its read
            if ghosts[i] == h:
                if cell == DONE:
                    ns = _set_item(state, i, DOUBLE)
                elif cell == PEND:
                    ns = _set_item(state, i, DONE)
                else:
                    holder = _claim_value(cell, cfg)
                    if holder is not None:
                        # the group stays claimed, but a completed delivery
                        # now exists: the holder's own finish doubles it
                        ns = _set_item(state, i, H + 3 + holder)
                    else:
                        ns = _set_item(state, i, DOUBLE)
                out.append((('ghost_deliver', h, i), _set_ghost(ns, i, -1)))

    # crash: a live host dies; its lease has NOT expired yet, so its claims
    # stay pinned (nobody may adopt them)
    if state[CRASHES_LEFT] > 0:
        for h in alive:
            ns = _set_host(state, h, CRASHED)
            ns = ns[:CRASHES_LEFT] + (state[CRASHES_LEFT] - 1,) \
                + ns[CRASHES_LEFT + 1:]
            out.append((('crash', h), ns))

    # lease expiry: a crashed host's claims return to the pool (that is the
    # exactly-once handoff point); with drop_on_expire they are wrongly
    # marked done instead
    for h in range(H):
        if hosts[h] == CRASHED:
            ns = _set_host(state, h, GONE)
            for i, cell in enumerate(items):
                if cell == h:
                    repl = DROPPED if cfg.mutation == 'drop_on_expire' else PEND
                    ns = _set_item(ns, i, repl)
                elif cell == H + 3 + h:
                    # the claim evaporates; the earlier delivery stands
                    ns = _set_item(ns, i, DONE)
            out.append((('expire', h), ns))
        # the reassign_before_expiry defect: the expiry action fires on a
        # host that is still ALIVE — its claims are released for adoption
        # while it keeps processing them (ghost delivery above)
        if cfg.mutation == 'reassign_before_expiry' and hosts[h] == ALIVE \
                and any(c == h for c in items):
            ns = state
            for i, cell in enumerate(items):
                if cell == h:
                    ns = _set_item(ns, i, PEND)
                    ns = _set_ghost(ns, i, h)
            out.append((('expire', h), ns))

    # join: a new host comes up and starts heartbeating
    if state[JOINS_LEFT] > 0:
        for h in range(H):
            if hosts[h] == OUT:
                ns = _set_host(state, h, ALIVE)
                ns = ns[:JOINS_LEFT] + (state[JOINS_LEFT] - 1,)
                out.append((('join', h), ns))

    # reshard: the alive set drifted from the current generation's member
    # set — advance the generation and re-pin the map to the alive set
    if alive and alive != state[GENSET]:
        new_gen = 0 if cfg.mutation == 'generation_rollback' else state[GEN] + 1
        flags = state[FLAGS]
        if new_gen <= state[GEN]:
            flags |= F_GEN_REGRESS
        ns = (new_gen, alive) + state[HOSTS:FLAGS] + (flags,) \
            + state[FLAGS + 1:]
        out.append((('reshard', new_gen, alive), ns))

    return [(label, canonicalize(ns)) for label, ns in out]


def check_state(state, cfg):
    """First violated safety invariant, or None."""
    H = cfg.hosts
    if any(cell == H + 2 for cell in state[ITEMS]):
        return 'exactly_once_coverage'
    for cell in state[ITEMS]:
        holder = _claim_value(cell, cfg)
        if holder is not None and state[HOSTS][holder] == GONE:
            return 'handoff_after_lease_expiry'
    if state[FLAGS] & F_GEN_REGRESS:
        return 'generation_monotonic'
    return None


def check_terminal(state, cfg):
    """Liveness at quiescence: with at least one surviving host, the epoch
    must have terminated with every row group delivered exactly once. A pod
    with NO survivors is vacuously fine (there is nobody left to finish)."""
    H = cfg.hosts
    if not any(s == ALIVE for s in state[HOSTS]):
        return None
    if any(cell == H + 1 for cell in state[ITEMS]):
        return 'exactly_once_coverage'     # done-without-delivery: dropped
    if any(cell != H for cell in state[ITEMS]):
        return 'epoch_termination'
    return None


class ElasticCheckResult(object):
    __slots__ = ('config', 'exhausted', 'states', 'transitions', 'depth',
                 'elapsed_s', 'violation', 'trace', 'terminal_states')

    def __init__(self, config):
        self.config = config
        self.exhausted = False
        self.states = 0
        self.transitions = 0
        self.depth = 0
        self.elapsed_s = 0.0
        self.violation = None
        self.trace = None
        self.terminal_states = 0

    @property
    def ok(self):
        return self.exhausted and self.violation is None

    def to_dict(self):
        return {'config': self.config.describe(), 'exhausted': self.exhausted,
                'states': self.states, 'transitions': self.transitions,
                'depth': self.depth, 'elapsed_s': round(self.elapsed_s, 3),
                'terminal_states': self.terminal_states,
                'violation': self.violation,
                'trace': [repr(l) for l in self.trace] if self.trace else None}


def check(cfg, budget_s=None, max_states=None):
    """Exhaustive BFS over every interleaving of the elastic pod system.
    BFS order makes the first counterexample length-minimal."""
    result = ElasticCheckResult(cfg)
    t0 = time.monotonic()
    init = canonicalize(initial_state(cfg))
    parents = {init: None}
    frontier = collections.deque([(init, 0)])
    result.states = 1
    violation, violating = check_state(init, cfg), None
    if violation:
        violating = init
    popped = 0
    while frontier and violation is None:
        state, depth = frontier.popleft()
        popped += 1
        result.depth = max(result.depth, depth)
        succ = successors(state, cfg)
        result.transitions += len(succ)
        if not succ:
            result.terminal_states += 1
            violation = check_terminal(state, cfg)
            if violation:
                violating = state
                break
        for label, ns in succ:
            if ns in parents:
                continue
            parents[ns] = (state, label)
            result.states += 1
            v = check_state(ns, cfg)
            if v is not None:
                violation, violating = v, ns
                break
            frontier.append((ns, depth + 1))
        if violation is None and popped % 2048 == 0:
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                break
            if max_states is not None and result.states >= max_states:
                break
    else:
        if violation is None:
            result.exhausted = True
    result.elapsed_s = time.monotonic() - t0
    if violation is not None:
        result.violation = violation
        trace = []
        s = violating
        while parents[s] is not None:
            s, label = parents[s]
            trace.append(label)
        trace.reverse()
        result.trace = trace
    return result


def random_walk(cfg, seed, max_steps=200):
    """One seeded schedule through the system: the trace walked and whether
    it ended in a violating state. Drives the monitor-conformance fuzz in
    ``tests/test_elastic.py``."""
    rng = random.Random(seed)
    state = initial_state(cfg)
    trace = []
    violation = check_state(state, cfg)
    for _ in range(max_steps):
        if violation is not None:
            break
        succ = successors(state, cfg)
        if not succ:
            violation = check_terminal(state, cfg)
            break
        label, state = succ[rng.randrange(len(succ))]
        trace.append(label)
        violation = check_state(state, cfg)
    return trace, violation


def replay_into_monitor(trace, monitor):
    """Replay a spec trace through an :class:`~petastorm_tpu.analysis.
    protocol.monitor.ElasticMonitor` — the event-projection glue that keeps
    the runtime monitor honest against the spec. Healthy traces must pass;
    mutant traces that reach an event-visible defect must raise
    :class:`~petastorm_tpu.errors.ProtocolViolation`."""
    for label in trace:
        kind = label[0]
        if kind == 'claim':
            monitor.on_claim(label[1], label[2])
        elif kind in ('deliver', 'ghost_deliver'):
            monitor.on_deliver(label[1], label[2])
        elif kind == 'expire':
            monitor.on_lease_expire(label[1])
        elif kind == 'join':
            monitor.on_join(label[1])
        elif kind == 'reshard':
            monitor.on_reshard(label[1], label[2])
        # 'crash' has no consumer-visible event: the lease just stops renewing


#: the tier-1 default scope (tests/test_elastic.py gates exhaustion + a
#: state floor on it, like the supervision and serve scopes)
DEFAULT_ELASTIC_SCOPE = dict(hosts=4, items=4, initial_alive=2, crashes=2,
                             joins=2)

#: the default scope must explore at least this many canonical states — the
#: regression tripwire against accidental transition pruning
DEFAULT_ELASTIC_STATE_FLOOR = 100_000

__all__ = ['DEFAULT_ELASTIC_SCOPE', 'DEFAULT_ELASTIC_STATE_FLOOR',
           'ElasticCheckResult', 'ElasticSpecConfig', 'INVARIANTS',
           'MUTATIONS', 'canonicalize', 'check', 'check_state',
           'check_terminal', 'initial_state', 'random_walk',
           'replay_into_monitor', 'successors']
