"""The canonical worker-pool wire protocol: message kinds, framing, dispatch ids.

This module is the SINGLE definition site for every constant of the
supervision protocol (docs/protocol.md). The pools (``process_pool.py``,
``thread_pool.py``, ``dummy_pool.py``), the test stubs
(``test_util/stub_workers.py``), the executable spec
(``petastorm_tpu/analysis/protocol/spec.py``) and the runtime conformance
monitor all import from here — lint rule PT801 flags any other definition
site, and PT800 flags consumer dispatch chains that miss a declared kind.

Protocol summary (full semantics in ``docs/protocol.md``):

* Workers send messages over a per-worker FIFO results channel (shm ring or
  zmq PUSH). The first byte of every message is its *kind*.
* Every ventilated item carries a pool-assigned *dispatch id* — monotonically
  increasing, NEVER reused. A requeued item gets a fresh id; any message
  tagged with a superseded id is stale and must be dropped.
* A worker claims the item it is processing (``MSG_HEARTBEAT`` with
  ``busy=<dispatch id>``) BEFORE processing; the item's ``MSG_DONE`` /
  ``MSG_ERROR`` implicitly releases the claim (the channel is FIFO, so the
  claim always precedes its item's completion).
* At spans level, the item's ``TraceContext`` rides the SAME records the
  dispatch id does — a reserved slot in the task/result tuples and dispatch
  frames, ``None`` below spans level — and the worker-side span events ship
  home on the existing ``MSG_METRICS`` piggyback. Causal tracing
  (docs/observability.md "Causal tracing") adds no message kinds and no
  extra queue traffic; ``tests/test_tracing.py`` pins this structurally.
"""

from __future__ import annotations

import struct

#: control-channel (PUB/SUB) shutdown broadcast — not a results-channel kind
CONTROL_FINISHED = b'FINISHED'

# -- results-channel message kinds (the first byte of every message) --------

MSG_STARTED = b'S'    #: startup handshake: worker connected and reported in
MSG_DATA = b'D'       #: an item's serialized payload, in-band
MSG_DONE = b'F'       #: item completion sentinel (releases the claim)
MSG_ERROR = b'E'      #: pickled worker-side exception report (releases the claim)
MSG_BLOB = b'B'       #: an item's payload parked in a /dev/shm blob; payload = path
MSG_METRICS = b'M'    #: cumulative telemetry snapshot piggyback
MSG_HEARTBEAT = b'H'  #: liveness + item-ownership beacon (claim when busy is set)

#: kind byte -> canonical lowercase name, in protocol order. THE exhaustive
#: declaration: PT800 checks consumer dispatch chains against this set, and
#: the spec/monitor use the names as their event vocabulary.
MESSAGE_KINDS = {
    MSG_STARTED: 'started',
    MSG_DATA: 'data',
    MSG_DONE: 'done',
    MSG_ERROR: 'error',
    MSG_BLOB: 'blob',
    MSG_METRICS: 'metrics',
    MSG_HEARTBEAT: 'heartbeat',
}

#: every declared kind byte
ALL_KINDS = tuple(MESSAGE_KINDS)

#: canonical constant name -> kind byte (what PT800/PT801 recognize in source)
KIND_CONSTANT_NAMES = {
    'MSG_STARTED': MSG_STARTED,
    'MSG_DATA': MSG_DATA,
    'MSG_DONE': MSG_DONE,
    'MSG_ERROR': MSG_ERROR,
    'MSG_BLOB': MSG_BLOB,
    'MSG_METRICS': MSG_METRICS,
    'MSG_HEARTBEAT': MSG_HEARTBEAT,
}

# -- serve-plane frame kinds (broadcast fan-out ring, docs/serve.md) --------
#
# NOT results-channel kinds: these frame daemon -> consumer broadcast traffic
# on the BcastRing and are deliberately kept out of MESSAGE_KINDS (the pool
# consumer loops never see them). Defined here because this module is the
# single definition site for every wire constant (PT801).

SERVE_DATA = b'd'    #: one decoded batch payload, in-band (serializer framing)
SERVE_BLOB = b'b'    #: one decoded batch parked in a shared /dev/shm blob;
                     #: payload = ``<size>|<path>`` — consumers COW-mmap it
                     #: (zero upfront copy) and the daemon reclaims the file
                     #: once the whole fleet's ring cursors passed the frame
SERVE_COLS = b'c'    #: a FUSED batch decoded DIRECTLY into a shared blob:
                     #: payload = pickled ``{'path','size','rows','cols'}``
                     #: column-layout descriptor; consumers view the mapping
                     #: in place — zero batch copies anywhere in the fan-out
SERVE_DONE = b'f'    #: item completion sentinel (carries the item seq)
SERVE_END = b'z'     #: per-tenant end of stream: the tenant's epochs finished
SERVE_ERROR = b'e'   #: pickled daemon-side error report; the stream is over

#: every serve-plane frame kind, in protocol order
SERVE_KINDS = (SERVE_DATA, SERVE_BLOB, SERVE_COLS, SERVE_DONE, SERVE_END,
               SERVE_ERROR)

# -- shm-ring framing -------------------------------------------------------

#: ring message header: kind byte + little-endian int64 dispatch id (-1 = None)
RING_HEADER_LEN = 9


def ring_header(kind, dispatch):
    """Ring message framing: kind byte + little-endian int64 dispatch id
    (-1 = None), then the payload; header and payload are gather-written as
    one message."""
    return kind + struct.pack('<q', -1 if dispatch is None else dispatch)


def ring_unpack(view):
    """(kind, dispatch, payload_view) from a message memoryview — the payload
    stays a zero-copy view handed straight to the deserializer."""
    dispatch = struct.unpack_from('<q', view, 1)[0]
    return bytes(view[0:1]), (None if dispatch < 0 else dispatch), view[RING_HEADER_LEN:]


# -- dispatch ids -----------------------------------------------------------

class DispatchIds(object):
    """Monotonic dispatch-id allocator. Ids are NEVER reused: a requeued item
    gets a fresh id so straggler messages from its previous attempt are
    recognizable as stale — the exactly-once invariant rests on this
    (``petastorm_tpu/analysis/protocol/spec.py`` proves it for small scopes).

    Not thread-safe by itself; callers allocate under their own state lock
    (the pools already hold one for the in-flight table the id keys into).
    """

    __slots__ = ('_next',)

    def __init__(self, start=0):
        self._next = start

    def next(self):
        d = self._next
        self._next += 1
        return d

    @property
    def issued(self):
        """How many ids have been allocated so far."""
        return self._next


__all__ = [
    'ALL_KINDS', 'CONTROL_FINISHED', 'DispatchIds', 'KIND_CONSTANT_NAMES',
    'MESSAGE_KINDS', 'MSG_BLOB', 'MSG_DATA', 'MSG_DONE', 'MSG_ERROR',
    'MSG_HEARTBEAT', 'MSG_METRICS', 'MSG_STARTED', 'RING_HEADER_LEN',
    'SERVE_BLOB', 'SERVE_COLS', 'SERVE_DATA', 'SERVE_DONE', 'SERVE_END',
    'SERVE_ERROR',
    'SERVE_KINDS',
    'ring_header', 'ring_unpack',
]
