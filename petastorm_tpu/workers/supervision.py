"""Worker supervision primitives shared by the thread/process/dummy pools.

The supervision model (see ``docs/robustness.md``) separates two failure
planes:

* **Item failures** — ``worker.process`` raised, or (process pools) the item
  killed its worker process. Governed by the uniform
  ``on_error='raise'|'skip'|'retry'`` / ``max_item_retries`` policy: ``raise``
  surfaces the first error to the consumer (the historical behavior);
  ``retry`` re-runs the item up to ``max_item_retries`` times before raising;
  ``skip`` re-runs, then *quarantines* — the item is recorded, counted
  complete so the epoch terminates, and the pipeline continues.
* **Infrastructure failures** — a worker process died (OOM kill, segfault)
  for reasons that may have nothing to do with the item it held. The process
  pool always respawns and requeues (see ``process_pool.py``); only when the
  SAME item keeps killing its workers does the item policy above apply.

Exactly-once accounting invariant: every ventilated item triggers exactly one
completion (``_DONE`` consumption / quarantine / error-completion) regardless
of how many times it was requeued — ``ConcurrentVentilator.processed_item``
and the pools' ``items_completed`` counters must never double-count a retry.
"""

from __future__ import annotations

import traceback

ON_ERROR_POLICIES = ('raise', 'skip', 'retry')

#: default consecutive-failure budget before an item is declared poison
DEFAULT_MAX_ITEM_RETRIES = 2


class ErrorPolicy(object):
    """Validated ``(on_error, max_item_retries)`` pair shared by every pool.

    ``attempts`` below counts *failed* attempts: an item is retried while
    ``attempts <= max_item_retries`` (so an item runs at most
    ``max_item_retries + 1`` times).
    """

    __slots__ = ('on_error', 'max_item_retries')

    def __init__(self, on_error='raise', max_item_retries=DEFAULT_MAX_ITEM_RETRIES):
        if on_error not in ON_ERROR_POLICIES:
            raise ValueError("on_error must be one of {}, got {!r}".format(
                ON_ERROR_POLICIES, on_error))
        if not isinstance(max_item_retries, int) or max_item_retries < 0:
            raise ValueError('max_item_retries must be a non-negative integer, '
                             'got {!r}'.format(max_item_retries))
        self.on_error = on_error
        self.max_item_retries = max_item_retries

    def should_retry_error(self, attempts):
        """Retry a *raised* item failure? ``raise`` never retries errors —
        its contract is the fastest possible surfacing of the first failure."""
        return self.on_error in ('retry', 'skip') and attempts <= self.max_item_retries

    def should_retry_crash(self, attempts):
        """Retry an item whose worker *died*? Crashes are retried under every
        policy (a respawn + requeue is the whole point of supervision); the
        budget only bounds how long a worker-killing item may crash-loop."""
        return attempts <= self.max_item_retries

    def quarantines(self):
        return self.on_error == 'skip'

    def __repr__(self):
        return 'ErrorPolicy(on_error={!r}, max_item_retries={})'.format(
            self.on_error, self.max_item_retries)


def quarantine_record(seq, attempts, kind, error=None, tb=None, worker_id=None,
                      item=None):
    """The structured error record emitted for a quarantined item — a plain
    picklable dict (it crosses the diagnostics surface and may be logged as
    JSON). ``kind`` is ``'error'`` (worker raised) or ``'crash'`` (worker
    process died)."""
    return {
        'seq': seq,
        'item': item,
        'attempts': attempts,
        'kind': kind,
        'error': None if error is None else '{}: {}'.format(type(error).__name__, error),
        'traceback': tb,
        'worker_id': worker_id,
    }


def format_exception_tb(exc):
    """The formatted traceback of a live exception (worker side, before the
    traceback is lost to pickling)."""
    return ''.join(traceback.format_exception(type(exc), exc, exc.__traceback__))


class RemoteWorkerError(Exception):
    """Carrier for a worker-side failure context. Installed as the
    ``__cause__`` of the re-raised worker exception, so the consumer's
    traceback renders the remote traceback first, then the local re-raise —
    nothing about where the failure actually happened is lost."""


def attach_remote_context(exc, tb, worker_id=None, seq=None, pid=None):
    """Annotate a worker exception re-raised on the consumer thread with its
    remote traceback and origin. Sets ``exc.worker_traceback`` /
    ``exc.worker_id`` / ``exc.item_seq`` and chains a
    :class:`RemoteWorkerError` cause holding the formatted remote traceback.
    Returns ``exc`` for ``raise attach_remote_context(...)`` use."""
    where = 'worker {}'.format(worker_id if worker_id is not None else '?')
    if pid is not None:
        where += ' (pid {})'.format(pid)
    if seq is not None:
        where += ' processing item seq={}'.format(seq)
    exc.worker_traceback = tb
    exc.worker_id = worker_id
    exc.item_seq = seq
    exc.__cause__ = RemoteWorkerError(
        '{} failed; worker-side traceback:\n{}'.format(where, tb or '<unavailable>'))
    return exc
