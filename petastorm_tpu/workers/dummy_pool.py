"""Dummy pool: synchronous execution on the CONSUMER thread.

Parity: /root/reference/petastorm/workers_pool/dummy_pool.py:20-91. Exists for
debugging and profiling — worker code runs where a profiler/debugger can see
it. That is why ``ventilate`` only ENQUEUES tasks: the actual
``worker.process`` happens inside :meth:`get_results` on the caller's thread
(with a ventilator attached, ``ventilate`` is invoked from the ventilator
thread — processing there would hide the hot loop from per-thread profilers
AND leave the consumer sleep-polling for results).

Item failures follow the pool-independent ``on_error``/``max_item_retries``
policy (``workers/supervision.py``) so reader behavior does not change when a
pipeline is dropped onto the dummy pool for debugging.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from petastorm_tpu import faults, observability as obs
from petastorm_tpu.errors import EmptyResultError
from petastorm_tpu.observability import blackbox
# canonical message-kind vocabulary + dispatch-id allocator (workers/protocol.py);
# PT801 rejects local kind definitions
from petastorm_tpu.workers.protocol import MSG_DATA, MSG_DONE, DispatchIds
from petastorm_tpu.workers.supervision import (ErrorPolicy, attach_remote_context,
                                               format_exception_tb, quarantine_record)

logger = logging.getLogger(__name__)


class DummyPool(object):
    def __init__(self, workers_count=1, results_queue_size=None,
                 on_error='raise', max_item_retries=None, protocol_monitor=None):
        self._results = deque()  # (MSG_DATA, seq, payload, ctx) | (MSG_DONE, seq, None, None)
        self._pending = deque()  # (dispatch, args, kwargs, attempts, ctx) (_seq rides kwargs)
        self._pending_lock = threading.Lock()
        # serializes worker.process against join()'s worker.shutdown: the
        # consumer thread may be mid-read inside native code (mmapped pages)
        # while ANOTHER thread tears the pool down — e.g. diagnose --watch,
        # whose pump thread iterates while the main thread exits the loader
        # context; shutting the worker (closing files/mappings) under its
        # feet is a segfault, not an exception
        self._exec_lock = threading.Lock()
        self._worker = None
        self._stopped = False
        self._ventilator = None
        self._worker_error = None
        self._current_seq = None
        self._current_dispatch = None
        self._current_published = False
        self._current_trace = None
        self._dispatch_ids = DispatchIds()
        self._ventilated_items = 0
        self._completed_items = 0
        self._items_requeued = 0
        self._quarantined = []
        self._policy = (on_error if isinstance(on_error, ErrorPolicy)
                        else ErrorPolicy(on_error, **({} if max_item_retries is None
                                                      else {'max_item_retries': max_item_retries})))
        self.workers_count = workers_count
        # checkpoint plumbing (see thread_pool.py)
        self.last_result_seq = None
        self.done_callback = None
        # trace linkage: virtual-root TraceContext of the last payload
        self.last_result_trace = None
        # opt-in protocol conformance monitor (docs/protocol.md). The dummy
        # pool runs worker.process on the consumer thread, so payloads enter
        # the results deque BEFORE the item's completion bookkeeping — the
        # delivery event therefore fires at publish time, not at pop time.
        import os
        self.protocol_monitor = None
        if protocol_monitor or (protocol_monitor is None and
                                os.environ.get('PSTPU_PROTOCOL_MONITOR', '') not in ('', '0')):
            from petastorm_tpu.analysis.protocol.monitor import monitor_from_env
            self.protocol_monitor = monitor_from_env(protocol_monitor, 'dummy-pool')

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._worker is not None:
            raise RuntimeError('Pool already started')
        flight = blackbox.maybe_enable('consumer')
        if flight is not None:
            flight.register_lock('dummy_pool.exec_lock', self._exec_lock)
            flight.watch('pool_completed', lambda: self._completed_items)
        with self._exec_lock:
            self._worker = worker_class(0, self._publish, worker_setup_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _publish(self, data):
        self._current_published = True
        if self.protocol_monitor is not None and self._current_dispatch is not None:
            self.protocol_monitor.on_message('data', self._current_dispatch, live=True)
        self._results.append((MSG_DATA, self._current_seq, data, self._current_trace))

    def ventilate(self, *args, **kwargs):
        # the ventilator's mint block is still active here: the context rides
        # the pending tuple — no extra queue traffic
        ctx = obs.current_trace()
        with self._pending_lock:
            self._ventilated_items += 1
            d = self._dispatch_ids.next()
            if self.protocol_monitor is not None:
                self.protocol_monitor.on_dispatch(d, dict(kwargs).get('_seq'))
            self._pending.append((d, args, kwargs, 0, ctx))

    def _process_one(self):
        """Run one pending task on THIS thread. Returns False when none were
        queued."""
        with self._pending_lock:
            if not self._pending:
                return False
            d, args, orig_kwargs, attempts, ctx = self._pending.popleft()
        kwargs = dict(orig_kwargs)
        self._current_seq = kwargs.pop('_seq', None)
        self._current_dispatch = d
        self._current_published = False
        self._current_trace = ctx
        completed = True
        delivered = False
        try:
            with self._exec_lock:
                worker = self._worker
                if worker is None:
                    return False  # joined concurrently: nothing left to run
                faults.on_item(kwargs)
                with obs.use_trace(ctx):
                    worker.process(*args, **kwargs)
            self._results.append((MSG_DONE, self._current_seq, None, None))
            delivered = True
        except Exception as e:  # noqa: BLE001 - routed through the error policy
            completed, delivered = self._handle_item_failure(e, d, args, orig_kwargs,
                                                             attempts + 1)
        finally:
            if completed:
                with self._pending_lock:
                    self._completed_items += 1
                    if self.protocol_monitor is not None:
                        self.protocol_monitor.on_complete(d, delivered)
                if self._ventilator is not None:
                    self._ventilator.processed_item(self._current_seq)
        return True

    def _handle_item_failure(self, exc, d, args, orig_kwargs, attempts):
        """Apply the on_error policy. Returns ``(completed, delivered)``:
        completed False means the item was requeued."""
        seq = self._current_seq
        if self._current_published and self._policy.on_error != 'raise':
            # publishes already landed in the results deque — a re-run would
            # deliver them twice (the protocol model checker's
            # requeue_published counterexample); complete delivered instead
            logger.warning('Item seq=%s failed AFTER publishing; completing the '
                           'item rather than re-running it: %s', seq, exc)
            self._results.append((MSG_DONE, seq, None, None))
            return True, True
        if self._policy.should_retry_error(attempts):
            logger.warning('Item seq=%s failed (attempt %d/%d); requeueing: %s',
                           seq, attempts, self._policy.max_item_retries + 1, exc)
            with self._pending_lock:
                nd = self._dispatch_ids.next()
                if self.protocol_monitor is not None:
                    self.protocol_monitor.on_requeue(d, nd)
                # retries keep the original TraceContext (same item, same tree)
                self._pending.append((nd, args, orig_kwargs, attempts,
                                      self._current_trace))
                self._items_requeued += 1
            obs.count('items_requeued')
            return False, False
        if self._policy.quarantines():
            record = quarantine_record(seq, attempts, 'error', error=exc,
                                       tb=format_exception_tb(exc), worker_id=0,
                                       item={'args': args, 'kwargs': orig_kwargs})
            with self._pending_lock:
                self._quarantined.append(record)
            obs.count('items_quarantined')
            logger.error('Quarantining item seq=%s after %d failed attempts: %s',
                         seq, attempts, record['error'])
            return True, False
        attach_remote_context(exc, format_exception_tb(exc), worker_id=0, seq=seq)
        self._worker_error = exc
        if self._ventilator is not None:
            self._ventilator.stop()
        return True, False

    def _pop_ready(self):
        """Pop queued entries until a payload is found; process completion
        sentinels on the way. Returns the payload or None."""
        while self._results:
            kind, seq, payload, ctx = self._results.popleft()
            if kind == MSG_DATA:
                self.last_result_seq = seq
                self.last_result_trace = obs.root_of(ctx)
                return payload
            if seq is not None and self.done_callback is not None:
                self.done_callback(seq)
        return None

    def get_results(self):
        # NOTE on attribution: the dummy pool runs worker.process on THIS
        # thread inside get_results, so the pool-wait timer here CONTAINS the
        # worker stage timers — which is exactly what the stall report's
        # proportional split over worker busy time expects.
        with obs.stage('pool_wait', cat='pool') as sp:
            payload = self._get_results()
            sp.link(self.last_result_trace)
            return payload

    def _get_results(self):
        while True:
            payload = self._pop_ready()
            if payload is not None:
                return payload
            if self._worker_error is not None:
                error, self._worker_error = self._worker_error, None
                raise error
            if self._process_one():
                continue  # produced results (or an error) synchronously
            if self._ventilator is None or self._ventilator.completed():
                # re-check: the ventilator may have enqueued between the
                # emptiness check and completed() flipping true
                if self._process_one():
                    continue
                payload = self._pop_ready()
                if payload is not None:
                    return payload
                if self._worker_error is not None:
                    error, self._worker_error = self._worker_error, None
                    raise error
                if self.protocol_monitor is not None and not self._stopped:
                    # after stop() the pending queue was deliberately dropped,
                    # so the drain is not a convergence claim
                    with self._pending_lock:
                        ventilated, completed = (self._ventilated_items,
                                                 self._completed_items)
                    self.protocol_monitor.on_drained(ventilated, completed)
                raise EmptyResultError()
            # brief wait: only reachable while the ventilator thread is between
            # enqueues (it does no processing, so this resolves in microseconds)
            time.sleep(0.0001)

    def stop(self):
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        # parity with ThreadPool (whose workers exit on the stop event): items
        # ventilated but not yet processed are dropped, not run after stop —
        # and a post-join get_results must raise EmptyResultError, not
        # AttributeError off the cleared worker
        with self._pending_lock:
            self._pending.clear()

    def join(self):
        with self._exec_lock:
            # under the exec lock: a consumer thread mid-process finishes its
            # item before the worker's files/mappings are torn down
            if self._worker is not None:
                self._worker.shutdown()
                self._worker = None

    @property
    def quarantined_items(self):
        """Structured records of quarantined items (``on_error='skip'``)."""
        with self._pending_lock:
            return list(self._quarantined)

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md)."""
        with self._pending_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
            requeued = self._items_requeued
            quarantined = len(self._quarantined)
        out = {'workers_count': self.workers_count,
               'items_ventilated': ventilated,
               'items_completed': completed,
               'items_in_flight': ventilated - completed,
               'results_queue_depth': len(self._results),
               'worker_restarts': 0,
               'items_requeued': requeued,
               'items_quarantined': quarantined}
        # the lifetime_* family is process-global (chunkstore mirrors, serve
        # blobs): surfaced by every pool type for one uniform schema
        from petastorm_tpu.native.lifetime import registry as lifetime_registry
        out.update(lifetime_registry().counters())
        return out

    def telemetry_snapshots(self):
        """Worker metrics already live in this process's registry."""
        return []

    @property
    def results_qsize(self):
        return len(self._results)
