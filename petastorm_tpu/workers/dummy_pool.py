"""Dummy pool: synchronous execution on the CONSUMER thread.

Parity: /root/reference/petastorm/workers_pool/dummy_pool.py:20-91. Exists for
debugging and profiling — worker code runs where a profiler/debugger can see
it. That is why ``ventilate`` only ENQUEUES tasks: the actual
``worker.process`` happens inside :meth:`get_results` on the caller's thread
(with a ventilator attached, ``ventilate`` is invoked from the ventilator
thread — processing there would hide the hot loop from per-thread profilers
AND leave the consumer sleep-polling for results).

Item failures follow the pool-independent ``on_error``/``max_item_retries``
policy (``workers/supervision.py``) so reader behavior does not change when a
pipeline is dropped onto the dummy pool for debugging.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from petastorm_tpu import faults, observability as obs
from petastorm_tpu.errors import EmptyResultError
from petastorm_tpu.workers.supervision import (ErrorPolicy, attach_remote_context,
                                               format_exception_tb, quarantine_record)

logger = logging.getLogger(__name__)

_DATA, _DONE = 0, 1


class DummyPool(object):
    def __init__(self, workers_count=1, results_queue_size=None,
                 on_error='raise', max_item_retries=None):
        self._results = deque()  # (_DATA, seq, payload) | (_DONE, seq, None)
        self._pending = deque()  # (args, kwargs, attempts) not yet processed (_seq rides kwargs)
        self._pending_lock = threading.Lock()
        self._worker = None
        self._ventilator = None
        self._worker_error = None
        self._current_seq = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._items_requeued = 0
        self._quarantined = []
        self._policy = (on_error if isinstance(on_error, ErrorPolicy)
                        else ErrorPolicy(on_error, **({} if max_item_retries is None
                                                      else {'max_item_retries': max_item_retries})))
        self.workers_count = workers_count
        # checkpoint plumbing (see thread_pool.py)
        self.last_result_seq = None
        self.done_callback = None

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._worker is not None:
            raise RuntimeError('Pool already started')
        self._worker = worker_class(
            0, lambda data: self._results.append((_DATA, self._current_seq, data)),
            worker_setup_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        with self._pending_lock:
            self._pending.append((args, kwargs, 0))
            self._ventilated_items += 1

    def _process_one(self):
        """Run one pending task on THIS thread. Returns False when none were
        queued."""
        with self._pending_lock:
            if not self._pending:
                return False
            args, orig_kwargs, attempts = self._pending.popleft()
        kwargs = dict(orig_kwargs)
        self._current_seq = kwargs.pop('_seq', None)
        completed = True
        try:
            faults.on_item(kwargs)
            self._worker.process(*args, **kwargs)
            self._results.append((_DONE, self._current_seq, None))
        except Exception as e:  # noqa: BLE001 - routed through the error policy
            completed = self._handle_item_failure(e, args, orig_kwargs, attempts + 1)
        finally:
            if completed:
                with self._pending_lock:
                    self._completed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
        return True

    def _handle_item_failure(self, exc, args, orig_kwargs, attempts):
        """Apply the on_error policy. Returns True when the item reached a
        terminal state (counts complete), False when it was requeued."""
        seq = self._current_seq
        if self._policy.should_retry_error(attempts):
            logger.warning('Item seq=%s failed (attempt %d/%d); requeueing: %s',
                           seq, attempts, self._policy.max_item_retries + 1, exc)
            with self._pending_lock:
                self._pending.append((args, orig_kwargs, attempts))
                self._items_requeued += 1
            obs.count('items_requeued')
            return False
        if self._policy.quarantines():
            record = quarantine_record(seq, attempts, 'error', error=exc,
                                       tb=format_exception_tb(exc), worker_id=0,
                                       item={'args': args, 'kwargs': orig_kwargs})
            with self._pending_lock:
                self._quarantined.append(record)
            obs.count('items_quarantined')
            logger.error('Quarantining item seq=%s after %d failed attempts: %s',
                         seq, attempts, record['error'])
            return True
        attach_remote_context(exc, format_exception_tb(exc), worker_id=0, seq=seq)
        self._worker_error = exc
        if self._ventilator is not None:
            self._ventilator.stop()
        return True

    def _pop_ready(self):
        """Pop queued entries until a payload is found; process completion
        sentinels on the way. Returns the payload or None."""
        while self._results:
            kind, seq, payload = self._results.popleft()
            if kind == _DATA:
                self.last_result_seq = seq
                return payload
            if seq is not None and self.done_callback is not None:
                self.done_callback(seq)
        return None

    def get_results(self):
        # NOTE on attribution: the dummy pool runs worker.process on THIS
        # thread inside get_results, so the pool-wait timer here CONTAINS the
        # worker stage timers — which is exactly what the stall report's
        # proportional split over worker busy time expects.
        with obs.stage('pool_wait', cat='pool'):
            return self._get_results()

    def _get_results(self):
        while True:
            payload = self._pop_ready()
            if payload is not None:
                return payload
            if self._worker_error is not None:
                error, self._worker_error = self._worker_error, None
                raise error
            if self._process_one():
                continue  # produced results (or an error) synchronously
            if self._ventilator is None or self._ventilator.completed():
                # re-check: the ventilator may have enqueued between the
                # emptiness check and completed() flipping true
                if self._process_one():
                    continue
                payload = self._pop_ready()
                if payload is not None:
                    return payload
                if self._worker_error is not None:
                    error, self._worker_error = self._worker_error, None
                    raise error
                raise EmptyResultError()
            # brief wait: only reachable while the ventilator thread is between
            # enqueues (it does no processing, so this resolves in microseconds)
            time.sleep(0.0001)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        # parity with ThreadPool (whose workers exit on the stop event): items
        # ventilated but not yet processed are dropped, not run after stop —
        # and a post-join get_results must raise EmptyResultError, not
        # AttributeError off the cleared worker
        with self._pending_lock:
            self._pending.clear()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()
            self._worker = None

    @property
    def quarantined_items(self):
        """Structured records of quarantined items (``on_error='skip'``)."""
        with self._pending_lock:
            return list(self._quarantined)

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md)."""
        with self._pending_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
            requeued = self._items_requeued
            quarantined = len(self._quarantined)
        return {'workers_count': self.workers_count,
                'items_ventilated': ventilated,
                'items_completed': completed,
                'items_in_flight': ventilated - completed,
                'results_queue_depth': len(self._results),
                'worker_restarts': 0,
                'items_requeued': requeued,
                'items_quarantined': quarantined}

    def telemetry_snapshots(self):
        """Worker metrics already live in this process's registry."""
        return []

    @property
    def results_qsize(self):
        return len(self._results)
