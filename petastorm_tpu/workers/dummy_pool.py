"""Dummy pool: synchronous execution on the caller thread.

Parity: /root/reference/petastorm/workers_pool/dummy_pool.py:20-91. Exists for
debugging and profiling — worker code runs where a profiler/debugger can see it.
"""

from __future__ import annotations

from collections import deque

from petastorm_tpu.workers.worker_base import EmptyResultError


_DATA, _DONE = 0, 1


class DummyPool(object):
    def __init__(self, workers_count=1, results_queue_size=None):
        self._results = deque()  # (_DATA, seq, payload) | (_DONE, seq, None)
        self._worker = None
        self._ventilator = None
        self._worker_error = None
        self._current_seq = None
        self.workers_count = workers_count
        # checkpoint plumbing (see thread_pool.py)
        self.last_result_seq = None
        self.done_callback = None

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._worker is not None:
            raise RuntimeError('Pool already started')
        self._worker = worker_class(
            0, lambda data: self._results.append((_DATA, self._current_seq, data)),
            worker_setup_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        self._current_seq = kwargs.pop('_seq', None)
        try:
            self._worker.process(*args, **kwargs)
            self._results.append((_DONE, self._current_seq, None))
        except Exception as e:  # noqa: BLE001 - forwarded to the consumer, like
            # ThreadPool/ProcessPool do; without this a ventilator-thread failure
            # would leave get_results() spinning forever
            self._worker_error = e
            if self._ventilator is not None:
                self._ventilator.stop()
            raise
        finally:
            if self._ventilator is not None:
                self._ventilator.processed_item()

    def _pop_ready(self):
        """Pop queued entries until a payload is found; process completion
        sentinels on the way. Returns the payload or None."""
        while self._results:
            kind, seq, payload = self._results.popleft()
            if kind == _DATA:
                self.last_result_seq = seq
                return payload
            if seq is not None and self.done_callback is not None:
                self.done_callback(seq)
        return None

    def get_results(self):
        # give a lazy ventilator thread a chance to feed us before declaring empty
        import time
        while True:
            payload = self._pop_ready()
            if payload is not None:
                return payload
            if self._worker_error is not None:
                error, self._worker_error = self._worker_error, None
                raise error
            if self._ventilator is None or self._ventilator.completed():
                # re-check: the ventilator may have appended a result between the
                # emptiness check and completed() flipping true
                payload = self._pop_ready()
                if payload is not None:
                    return payload
                if self._worker_error is not None:
                    error, self._worker_error = self._worker_error, None
                    raise error
                raise EmptyResultError()
            time.sleep(0.0001)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()
            self._worker = None

    @property
    def diagnostics(self):
        return {'output_queue_size': len(self._results)}

    @property
    def results_qsize(self):
        return len(self._results)
