"""Dummy pool: synchronous execution on the caller thread.

Parity: /root/reference/petastorm/workers_pool/dummy_pool.py:20-91. Exists for
debugging and profiling — worker code runs where a profiler/debugger can see it.
"""

from __future__ import annotations

from collections import deque

from petastorm_tpu.workers.worker_base import EmptyResultError


class DummyPool(object):
    def __init__(self, workers_count=1, results_queue_size=None):
        self._results = deque()
        self._worker = None
        self._ventilator = None
        self._worker_error = None
        self.workers_count = workers_count

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._worker is not None:
            raise RuntimeError('Pool already started')
        self._worker = worker_class(0, self._results.append, worker_setup_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        try:
            self._worker.process(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - forwarded to the consumer, like
            # ThreadPool/ProcessPool do; without this a ventilator-thread failure
            # would leave get_results() spinning forever
            self._worker_error = e
            if self._ventilator is not None:
                self._ventilator.stop()
            raise
        finally:
            if self._ventilator is not None:
                self._ventilator.processed_item()

    def get_results(self):
        # give a lazy ventilator thread a chance to feed us before declaring empty
        import time
        while not self._results:
            if self._worker_error is not None:
                error, self._worker_error = self._worker_error, None
                raise error
            if self._ventilator is None or self._ventilator.completed():
                # re-check: the ventilator may have appended a result between the
                # emptiness check and completed() flipping true
                if self._results:
                    break
                if self._worker_error is not None:
                    error, self._worker_error = self._worker_error, None
                    raise error
                raise EmptyResultError()
            time.sleep(0.001)
        return self._results.popleft()

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()
            self._worker = None

    @property
    def diagnostics(self):
        return {'output_queue_size': len(self._results)}

    @property
    def results_qsize(self):
        return len(self._results)
