"""Dummy pool: synchronous execution on the CONSUMER thread.

Parity: /root/reference/petastorm/workers_pool/dummy_pool.py:20-91. Exists for
debugging and profiling — worker code runs where a profiler/debugger can see
it. That is why ``ventilate`` only ENQUEUES tasks: the actual
``worker.process`` happens inside :meth:`get_results` on the caller's thread
(with a ventilator attached, ``ventilate`` is invoked from the ventilator
thread — processing there would hide the hot loop from per-thread profilers
AND leave the consumer sleep-polling for results).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from petastorm_tpu import observability as obs
from petastorm_tpu.workers.worker_base import EmptyResultError


_DATA, _DONE = 0, 1


class DummyPool(object):
    def __init__(self, workers_count=1, results_queue_size=None):
        self._results = deque()  # (_DATA, seq, payload) | (_DONE, seq, None)
        self._pending = deque()  # (args, kwargs) not yet processed (_seq rides kwargs)
        self._pending_lock = threading.Lock()
        self._worker = None
        self._ventilator = None
        self._worker_error = None
        self._current_seq = None
        self._ventilated_items = 0
        self._completed_items = 0
        self.workers_count = workers_count
        # checkpoint plumbing (see thread_pool.py)
        self.last_result_seq = None
        self.done_callback = None

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._worker is not None:
            raise RuntimeError('Pool already started')
        self._worker = worker_class(
            0, lambda data: self._results.append((_DATA, self._current_seq, data)),
            worker_setup_args)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        with self._pending_lock:
            self._pending.append((args, kwargs))
            self._ventilated_items += 1

    def _process_one(self):
        """Run one pending task on THIS thread. Returns False when none were
        queued."""
        with self._pending_lock:
            if not self._pending:
                return False
            args, kwargs = self._pending.popleft()
        kwargs = dict(kwargs)
        self._current_seq = kwargs.pop('_seq', None)
        try:
            self._worker.process(*args, **kwargs)
            self._results.append((_DONE, self._current_seq, None))
        except Exception as e:  # noqa: BLE001 - forwarded like Thread/ProcessPool
            self._worker_error = e
            if self._ventilator is not None:
                self._ventilator.stop()
        finally:
            with self._pending_lock:
                self._completed_items += 1
            if self._ventilator is not None:
                self._ventilator.processed_item()
        return True

    def _pop_ready(self):
        """Pop queued entries until a payload is found; process completion
        sentinels on the way. Returns the payload or None."""
        while self._results:
            kind, seq, payload = self._results.popleft()
            if kind == _DATA:
                self.last_result_seq = seq
                return payload
            if seq is not None and self.done_callback is not None:
                self.done_callback(seq)
        return None

    def get_results(self):
        # NOTE on attribution: the dummy pool runs worker.process on THIS
        # thread inside get_results, so the pool-wait timer here CONTAINS the
        # worker stage timers — which is exactly what the stall report's
        # proportional split over worker busy time expects.
        with obs.stage('pool_wait', cat='pool'):
            return self._get_results()

    def _get_results(self):
        while True:
            payload = self._pop_ready()
            if payload is not None:
                return payload
            if self._worker_error is not None:
                error, self._worker_error = self._worker_error, None
                raise error
            if self._process_one():
                continue  # produced results (or an error) synchronously
            if self._ventilator is None or self._ventilator.completed():
                # re-check: the ventilator may have enqueued between the
                # emptiness check and completed() flipping true
                if self._process_one():
                    continue
                payload = self._pop_ready()
                if payload is not None:
                    return payload
                if self._worker_error is not None:
                    error, self._worker_error = self._worker_error, None
                    raise error
                raise EmptyResultError()
            # brief wait: only reachable while the ventilator thread is between
            # enqueues (it does no processing, so this resolves in microseconds)
            time.sleep(0.0001)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        # parity with ThreadPool (whose workers exit on the stop event): items
        # ventilated but not yet processed are dropped, not run after stop —
        # and a post-join get_results must raise EmptyResultError, not
        # AttributeError off the cleared worker
        with self._pending_lock:
            self._pending.clear()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()
            self._worker = None

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md)."""
        with self._pending_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
        return {'workers_count': self.workers_count,
                'items_ventilated': ventilated,
                'items_completed': completed,
                'items_in_flight': ventilated - completed,
                'results_queue_depth': len(self._results)}

    def telemetry_snapshots(self):
        """Worker metrics already live in this process's registry."""
        return []

    @property
    def results_qsize(self):
        return len(self._results)
