"""Execution runtime: worker pools and the ventilator.

Parity: /root/reference/petastorm/workers_pool/ — a uniform
``start/ventilate/get_results/stop/join`` pool protocol over threads, spawned
processes (ZMQ transport), or the caller thread (dummy), fed by a
``ConcurrentVentilator`` with bounded in-flight work.
"""

from petastorm_tpu.workers.worker_base import WorkerBase, EmptyResultError  # noqa: F401
from petastorm_tpu.workers.thread_pool import ThreadPool  # noqa: F401
from petastorm_tpu.workers.dummy_pool import DummyPool  # noqa: F401
from petastorm_tpu.workers.process_pool import ProcessPool  # noqa: F401
from petastorm_tpu.workers.ventilator import ConcurrentVentilator  # noqa: F401
