"""Execution runtime: worker pools, the ventilator, and worker supervision.

Parity: /root/reference/petastorm/workers_pool/ — a uniform
``start/ventilate/get_results/stop/join`` pool protocol over threads, spawned
processes (shm-ring/ZMQ transport), or the caller thread (dummy), fed by a
``ConcurrentVentilator`` with bounded in-flight work.

Beyond the reference: the process pool supervises its workers (heartbeats,
exitcode polling, respawn + exactly-once requeue), and every pool implements
the uniform ``on_error``/``max_item_retries`` item-failure policy with
poison-item quarantine — see ``docs/robustness.md``. The supervision wire
protocol itself is canonical in :mod:`petastorm_tpu.workers.protocol` and
formally checked by ``petastorm_tpu/analysis/protocol/`` (executable spec,
exhaustive small-scope model checker, opt-in runtime conformance monitor via
``protocol_monitor=``/``PSTPU_PROTOCOL_MONITOR`` — ``docs/protocol.md``).
"""

from petastorm_tpu.workers import protocol  # noqa: F401
from petastorm_tpu.workers.worker_base import WorkerBase, EmptyResultError  # noqa: F401
from petastorm_tpu.workers.supervision import ErrorPolicy  # noqa: F401
from petastorm_tpu.workers.thread_pool import ThreadPool  # noqa: F401
from petastorm_tpu.workers.dummy_pool import DummyPool  # noqa: F401
from petastorm_tpu.workers.process_pool import ProcessPool  # noqa: F401
from petastorm_tpu.workers.ventilator import ConcurrentVentilator  # noqa: F401
