"""Thread pool: N daemon worker threads with a bounded results queue.

Parity: /root/reference/petastorm/workers_pool/thread_pool.py (worker exceptions
forwarded through the results queue and re-raised in the consumer :68-73,169-172;
per-item completion sentinel :63; stop-aware blocking put :200-214; optional
per-thread cProfile :41-49,190-198; ``diagnostics`` :219-221).

Threads are the right default on the TPU host: the hot work (Parquet decode,
image decode) happens in Arrow/OpenCV C++ which releases the GIL.

Item failures follow the pool-independent ``on_error``/``max_item_retries``
policy (``workers/supervision.py``): 'raise' forwards the first error to the
consumer (the historical behavior), 'retry' re-enqueues the item up to the
budget, 'skip' quarantines it after the budget so the epoch completes.
Threads cannot die the way processes can, so there is no heartbeat/respawn
machinery here — an exception IS the totality of a thread worker's failure
modes.
"""

from __future__ import annotations

import logging
import os
import pstats
import queue
import sys
import threading

from petastorm_tpu import faults, observability as obs
from petastorm_tpu.errors import EmptyResultError, WorkerTerminationRequested
from petastorm_tpu.observability import blackbox
# in-process pools speak the same canonical message-kind vocabulary as the
# wire protocol (workers/protocol.py): results-queue records are
# (kind, seq, payload, dispatch_id, trace_ctx) tuples, dispatch ids are
# allocated by the shared monotonic allocator, and PT801 rejects local kind
# definitions. The trace_ctx slot carries the item's TraceContext on MSG_DATA
# — context rides the existing record, never an extra message
from petastorm_tpu.workers.protocol import MSG_DATA, MSG_DONE, MSG_ERROR, DispatchIds
from petastorm_tpu.workers.supervision import (ErrorPolicy, attach_remote_context,
                                               format_exception_tb, quarantine_record)

logger = logging.getLogger(__name__)

DEFAULT_RESULTS_QUEUE_SIZE = 50

#: task-queue sentinel consumed by exactly one worker thread, which then
#: exits its loop (the retire half of the autotuner's worker knob)
_RETIRE = object()


class ThreadPool(object):
    def __init__(self, workers_count, results_queue_size=DEFAULT_RESULTS_QUEUE_SIZE,
                 profiling_enabled=False, on_error='raise', max_item_retries=None,
                 protocol_monitor=None):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._task_queue = queue.Queue()
        self._stop_event = threading.Event()
        self._threads = []
        self._ventilator = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._items_requeued = 0
        self._quarantined = []
        self._policy = (on_error if isinstance(on_error, ErrorPolicy)
                        else ErrorPolicy(on_error, **({} if max_item_retries is None
                                                      else {'max_item_retries': max_item_retries})))
        self._counter_lock = threading.Lock()
        self._next_worker_id = workers_count  # ids for runtime-grown slots
        self._dispatch_ids = DispatchIds()
        self._tls = threading.local()  # per-worker-thread current item seq
        # opt-in protocol conformance monitor (docs/protocol.md; lazy import so
        # the default path never loads the analysis stack)
        self.protocol_monitor = None
        if protocol_monitor or (protocol_monitor is None and
                                os.environ.get('PSTPU_PROTOCOL_MONITOR', '') not in ('', '0')):
            from petastorm_tpu.analysis.protocol.monitor import monitor_from_env
            self.protocol_monitor = monitor_from_env(protocol_monitor, 'thread-pool')
        # checkpoint plumbing: seq of the payload last returned by get_results,
        # and an optional callback fired when an item's completion sentinel is
        # consumed (used by results-queue readers to mark empty items delivered)
        self.last_result_seq = None
        self.done_callback = None
        # trace linkage: virtual-root TraceContext of the item whose payload
        # get_results last returned (None below spans level)
        self.last_result_trace = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._threads:
            raise RuntimeError('Pool already started')
        # flight recorder (docs/observability.md): threads share the consumer
        # process, so one recorder covers pool + consumer
        flight = blackbox.maybe_enable('consumer')
        if flight is not None:
            flight.register_lock('thread_pool.counter_lock', self._counter_lock)
            flight.watch('pool_completed', lambda: self._completed_items)
        # kept for runtime slot growth (add_worker_slot spawns identical workers)
        self._worker_class = worker_class
        self._worker_setup_args = worker_setup_args
        for worker_id in range(self._workers_count):
            worker = worker_class(worker_id, self._publish, worker_setup_args)
            thread = threading.Thread(target=self._worker_loop, args=(worker,), daemon=True)
            thread.start()
            self._threads.append(thread)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    # -- runtime slot grow/retire (the autotuner's worker knob) --------------

    def add_worker_slot(self):
        """Start one additional worker thread at runtime. Returns the new
        ``workers_count``. Safe at any point: the new worker pulls from the
        shared task queue exactly like the original ones."""
        if not self._threads:
            raise RuntimeError('Pool not started')
        with self._counter_lock:
            worker_id = self._next_worker_id
            self._next_worker_id += 1
            self._workers_count += 1
        worker = self._worker_class(worker_id, self._publish, self._worker_setup_args)
        thread = threading.Thread(target=self._worker_loop, args=(worker,), daemon=True)
        thread.start()
        self._threads.append(thread)
        logger.info('thread pool grew to %d workers', self._workers_count)
        return self._workers_count

    def retire_worker_slot(self):
        """Retire one worker thread at runtime (never below 1). The retire
        rides the task queue as a sentinel, so the exiting thread finishes
        its current item first — no item is ever abandoned. Returns the new
        ``workers_count``."""
        with self._counter_lock:
            if self._workers_count <= 1:
                return self._workers_count
            self._workers_count -= 1
        self._task_queue.put(_RETIRE)
        logger.info('thread pool retiring one worker (target %d)', self._workers_count)
        return self._workers_count

    def ventilate(self, *args, **kwargs):
        seq = kwargs.pop('_seq', None)
        # ventilate runs inside the ventilator's mint block, so the active
        # context here IS this item's identity; it rides the existing task
        # tuple — no extra queue traffic (the structural-overhead guard in
        # tests/test_tracing.py counts on this)
        ctx = obs.current_trace()
        with self._counter_lock:
            self._ventilated_items += 1
            d = self._dispatch_ids.next()
            if self.protocol_monitor is not None:
                # under the lock: allocation + dispatch event must be atomic
                # or concurrent ventilates report ids out of order
                self.protocol_monitor.on_dispatch(d, seq)
        self._task_queue.put((d, seq, args, kwargs, 0, ctx))

    def get_results(self):
        """Block until a result is available; raise :class:`EmptyResultError` when
        all ventilated items are processed and no more will be ventilated."""
        # the pool-wait stage timer is what the stall report decomposes the
        # loader's reader_wait_s against (docs/observability.md)
        with obs.stage('pool_wait', cat='pool') as sp:
            payload = self._get_results()
            # the item is only known once its frame arrives, so the wait span
            # joins its tree retroactively
            sp.link(self.last_result_trace)
            return payload

    def _get_results(self):
        while True:
            try:
                kind, seq, payload, d, ctx = self._results_queue.get(block=False)
            except queue.Empty:
                if self._all_done():
                    if self.protocol_monitor is not None and not self._stop_event.is_set():
                        with self._counter_lock:
                            ventilated, completed = (self._ventilated_items,
                                                     self._completed_items)
                        self.protocol_monitor.on_drained(ventilated, completed)
                    raise EmptyResultError()
                try:
                    kind, seq, payload, d, ctx = self._results_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            if kind == MSG_DATA:
                if self.protocol_monitor is not None:
                    self.protocol_monitor.on_message('data', d, live=True)
                self.last_result_seq = seq
                self.last_result_trace = obs.root_of(ctx)
                return payload
            elif kind == MSG_DONE:
                if self.protocol_monitor is not None:
                    self.protocol_monitor.on_message('done', d, live=True)
                # MSG_DONE payload is the delivered flag: quarantined/raised
                # items complete undelivered but still carry their real seq
                # for tenant-aware ventilator budget release
                self._count_completed(seq, d, delivered=bool(payload))
            elif kind == MSG_ERROR:
                if self.protocol_monitor is not None and d is not None:
                    self.protocol_monitor.on_message('error', d, live=True)
                raise payload
            else:
                # PT800-exhaustive: protocol.py declares no other in-process
                # kind; reaching this is a framing bug, never a silent drop
                raise RuntimeError('unknown results-queue kind {!r}'.format(kind))

    def _count_completed(self, seq=None, dispatch=None, delivered=True):
        with self._counter_lock:
            self._completed_items += 1
            if self.protocol_monitor is not None and dispatch is not None:
                self.protocol_monitor.on_complete(dispatch, delivered=delivered)
        if self._ventilator is not None:
            self._ventilator.processed_item(seq)
        if delivered and seq is not None and self.done_callback is not None:
            self.done_callback(seq)

    def _all_done(self):
        # completed() MUST be read before the counters: once it is true the
        # ventilated count is final, so a subsequent counter read cannot be
        # stale. The reverse order is a termination race — a whole epoch can
        # ventilate between a counters read of (0, 0) and completed()
        # flipping true, and the reader gives up with every item in flight
        # (found by the schedule explorer, docs/analysis.md).
        if self._ventilator is not None and not self._ventilator.completed():
            return False
        with self._counter_lock:
            outstanding = self._ventilated_items > self._completed_items
        if outstanding or not self._results_queue.empty():
            return False
        return True

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('join() must be called after stop()')
        # drain the results queue so workers blocked on a full queue can exit
        for thread in self._threads:
            while thread.is_alive():
                try:
                    while True:
                        self._results_queue.get(block=False)
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)
        self._threads = []
        if self._profiling_enabled and self._profiles:
            stats = pstats.Stats(*self._profiles)
            stats.sort_stats('cumulative').print_stats()

    @property
    def quarantined_items(self):
        """Structured records of quarantined items (``on_error='skip'``)."""
        with self._counter_lock:
            return list(self._quarantined)

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md): every
        pool type reports the same keys and units. ``worker_restarts`` is
        always 0 here — threads fail by exception, never by death."""
        with self._counter_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
            requeued = self._items_requeued
            quarantined = len(self._quarantined)
        out = {'workers_count': self._workers_count,
               'items_ventilated': ventilated,
               'items_completed': completed,
               'items_in_flight': ventilated - completed,
               'results_queue_depth': self._results_queue.qsize(),
               'worker_restarts': 0,
               'items_requeued': requeued,
               'items_quarantined': quarantined}
        # the lifetime_* family is process-global (chunkstore mirrors, serve
        # blobs): surfaced by every pool type for one uniform schema
        from petastorm_tpu.native.lifetime import registry as lifetime_registry
        out.update(lifetime_registry().counters())
        return out

    def telemetry_snapshots(self):
        """Worker metrics already live in this process's registry."""
        return []

    @property
    def results_qsize(self):
        return self._results_queue.qsize()

    # -- worker side --------------------------------------------------------

    def _publish(self, data):
        self._tls.published = True
        self._stop_aware_put((MSG_DATA, getattr(self._tls, 'seq', None), data,
                              getattr(self._tls, 'dispatch', None),
                              getattr(self._tls, 'trace', None)))

    def _stop_aware_put(self, item):
        """Bounded put that aborts when the pool is stopping, so workers never
        deadlock against a full results queue (reference thread_pool.py:200-214)."""
        while not self._stop_event.is_set():
            try:
                self._results_queue.put(item, timeout=0.05)
                return
            except queue.Full:
                continue
        raise WorkerTerminationRequested()

    def _handle_item_failure(self, worker, d, seq, args, kwargs, attempts, ctx):
        """Apply the on_error policy to one failed item, on the worker thread.
        ``attempts`` counts this failure. May raise WorkerTerminationRequested
        (propagated by the loop)."""
        exc = sys.exc_info()[1]
        if getattr(self._tls, 'published', False) and self._policy.on_error != 'raise':
            # the item already published into the results queue — requeueing
            # would run it (and its publishes) again, delivering rows twice;
            # it completes delivered instead, like a crash after publish on
            # the process pool (the protocol model checker's
            # requeue_published counterexample)
            logger.warning('Worker %d failed on item seq=%s AFTER publishing; '
                           'completing the item rather than re-running it: %s',
                           worker.worker_id, seq, exc)
            self._stop_aware_put((MSG_DONE, seq, True, d, None))
            return
        if self._policy.should_retry_error(attempts):
            logger.warning('Worker %d failed on item seq=%s (attempt %d/%d); requeueing: %s',
                           worker.worker_id, seq, attempts,
                           self._policy.max_item_retries + 1, exc)
            with self._counter_lock:
                self._items_requeued += 1
                nd = self._dispatch_ids.next()
                if self.protocol_monitor is not None:
                    self.protocol_monitor.on_requeue(d, nd)
            obs.count('items_requeued')
            # the retry keeps the original TraceContext: it is the same item,
            # and its (eventual) spans must land in the same tree
            self._task_queue.put((nd, seq, args, kwargs, attempts, ctx))
            return
        if self._policy.quarantines():
            record = quarantine_record(seq, attempts, 'error', error=exc,
                                       tb=format_exception_tb(exc),
                                       worker_id=worker.worker_id,
                                       item={'args': args, 'kwargs': kwargs})
            with self._counter_lock:
                self._quarantined.append(record)
            obs.count('items_quarantined')
            logger.error('Quarantining item seq=%s after %d failed attempts: %s',
                         seq, attempts, record['error'])
            # undelivered completion sentinel: the item counts complete for
            # epoch/flow-control/tenant-budget accounting but is never marked
            # delivered (the delivered flag, not a dropped seq, encodes that)
            self._stop_aware_put((MSG_DONE, seq, False, d, None))
            return
        logger.exception('Worker %d failed processing an item', worker.worker_id)
        attach_remote_context(exc, format_exception_tb(exc),
                              worker_id=worker.worker_id, seq=seq)
        self._stop_aware_put((MSG_ERROR, None, exc, d, None))
        # undelivered sentinel: flow control counts the item but it is
        # NOT marked delivered — a checkpoint will re-read it
        self._stop_aware_put((MSG_DONE, seq, False, d, None))

    def _worker_loop(self, worker):
        profiler = None
        if self._profiling_enabled:
            import cProfile
            profiler = cProfile.Profile()
        try:
            while not self._stop_event.is_set():
                try:
                    task = self._task_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if task is _RETIRE:
                    return  # deliberate slot retire (worker.shutdown in finally)
                d, seq, args, kwargs, attempts, ctx = task
                self._tls.seq = seq
                self._tls.dispatch = d
                self._tls.published = False
                self._tls.trace = ctx
                try:
                    if profiler is not None:
                        profiler.enable()
                    try:
                        faults.on_item(kwargs)
                        # worker stages (read/decode/transform) open under the
                        # item's context and land in its span tree
                        with obs.use_trace(ctx):
                            worker.process(*args, **kwargs)
                    finally:
                        if profiler is not None:
                            profiler.disable()
                    self._stop_aware_put((MSG_DONE, seq, True, d, None))
                except WorkerTerminationRequested:
                    return
                except Exception:  # noqa: BLE001 - routed through the error policy
                    try:
                        self._handle_item_failure(worker, d, seq, args, kwargs,
                                                  attempts + 1, ctx)
                    except WorkerTerminationRequested:
                        return
        finally:
            if profiler is not None:
                self._profiles.append(pstats.Stats(profiler))
            worker.shutdown()
