"""Thread pool: N daemon worker threads with a bounded results queue.

Parity: /root/reference/petastorm/workers_pool/thread_pool.py (worker exceptions
forwarded through the results queue and re-raised in the consumer :68-73,169-172;
per-item completion sentinel :63; stop-aware blocking put :200-214; optional
per-thread cProfile :41-49,190-198; ``diagnostics`` :219-221).

Threads are the right default on the TPU host: the hot work (Parquet decode,
image decode) happens in Arrow/OpenCV C++ which releases the GIL.
"""

from __future__ import annotations

import logging
import pstats
import queue
import sys
import threading

from petastorm_tpu import observability as obs
from petastorm_tpu.workers.worker_base import (EmptyResultError, WorkerTerminationRequested)

logger = logging.getLogger(__name__)

_DATA, _DONE, _ERROR = 0, 1, 2
DEFAULT_RESULTS_QUEUE_SIZE = 50


class ThreadPool(object):
    def __init__(self, workers_count, results_queue_size=DEFAULT_RESULTS_QUEUE_SIZE, profiling_enabled=False):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._task_queue = queue.Queue()
        self._stop_event = threading.Event()
        self._threads = []
        self._ventilator = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._counter_lock = threading.Lock()
        self._tls = threading.local()  # per-worker-thread current item seq
        # checkpoint plumbing: seq of the payload last returned by get_results,
        # and an optional callback fired when an item's completion sentinel is
        # consumed (used by results-queue readers to mark empty items delivered)
        self.last_result_seq = None
        self.done_callback = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._threads:
            raise RuntimeError('Pool already started')
        for worker_id in range(self._workers_count):
            worker = worker_class(worker_id, self._publish, worker_setup_args)
            thread = threading.Thread(target=self._worker_loop, args=(worker,), daemon=True)
            thread.start()
            self._threads.append(thread)
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def ventilate(self, *args, **kwargs):
        seq = kwargs.pop('_seq', None)
        with self._counter_lock:
            self._ventilated_items += 1
        self._task_queue.put((seq, args, kwargs))

    def get_results(self):
        """Block until a result is available; raise :class:`EmptyResultError` when
        all ventilated items are processed and no more will be ventilated."""
        # the pool-wait stage timer is what the stall report decomposes the
        # loader's reader_wait_s against (docs/observability.md)
        with obs.stage('pool_wait', cat='pool'):
            return self._get_results()

    def _get_results(self):
        while True:
            try:
                kind, seq, payload = self._results_queue.get(block=False)
            except queue.Empty:
                if self._all_done():
                    raise EmptyResultError()
                try:
                    kind, seq, payload = self._results_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            if kind == _DATA:
                self.last_result_seq = seq
                return payload
            elif kind == _DONE:
                self._count_completed(seq)
            else:  # _ERROR
                raise payload

    def _count_completed(self, seq=None):
        with self._counter_lock:
            self._completed_items += 1
        if self._ventilator is not None:
            self._ventilator.processed_item()
        if seq is not None and self.done_callback is not None:
            self.done_callback(seq)

    def _all_done(self):
        with self._counter_lock:
            outstanding = self._ventilated_items > self._completed_items
        if outstanding or not self._results_queue.empty():
            return False
        if self._ventilator is not None and not self._ventilator.completed():
            return False
        return True

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('join() must be called after stop()')
        # drain the results queue so workers blocked on a full queue can exit
        for thread in self._threads:
            while thread.is_alive():
                try:
                    while True:
                        self._results_queue.get(block=False)
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)
        self._threads = []
        if self._profiling_enabled and self._profiles:
            stats = pstats.Stats(*self._profiles)
            stats.sort_stats('cumulative').print_stats()

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md): every
        pool type reports the same keys and units."""
        with self._counter_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
        return {'workers_count': self._workers_count,
                'items_ventilated': ventilated,
                'items_completed': completed,
                'items_in_flight': ventilated - completed,
                'results_queue_depth': self._results_queue.qsize()}

    def telemetry_snapshots(self):
        """Worker metrics already live in this process's registry."""
        return []

    @property
    def results_qsize(self):
        return self._results_queue.qsize()

    # -- worker side --------------------------------------------------------

    def _publish(self, data):
        self._stop_aware_put((_DATA, getattr(self._tls, 'seq', None), data))

    def _stop_aware_put(self, item):
        """Bounded put that aborts when the pool is stopping, so workers never
        deadlock against a full results queue (reference thread_pool.py:200-214)."""
        while not self._stop_event.is_set():
            try:
                self._results_queue.put(item, timeout=0.05)
                return
            except queue.Full:
                continue
        raise WorkerTerminationRequested()

    def _worker_loop(self, worker):
        profiler = None
        if self._profiling_enabled:
            import cProfile
            profiler = cProfile.Profile()
        try:
            while not self._stop_event.is_set():
                try:
                    seq, args, kwargs = self._task_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                self._tls.seq = seq
                try:
                    if profiler is not None:
                        profiler.enable()
                    try:
                        worker.process(*args, **kwargs)
                    finally:
                        if profiler is not None:
                            profiler.disable()
                    self._stop_aware_put((_DONE, seq, None))
                except WorkerTerminationRequested:
                    return
                except Exception:  # noqa: BLE001 - forwarded to consumer
                    exc = sys.exc_info()[1]
                    logger.exception('Worker %d failed processing an item', worker.worker_id)
                    try:
                        self._stop_aware_put((_ERROR, None, exc))
                        # seq-less sentinel: flow control counts the item but it is
                        # NOT marked delivered — a checkpoint will re-read it
                        self._stop_aware_put((_DONE, None, None))
                    except WorkerTerminationRequested:
                        return
        finally:
            if profiler is not None:
                self._profiles.append(pstats.Stats(profiler))
            worker.shutdown()
