"""Ventilator: feeds work items into a pool with bounded in-flight count.

Parity: /root/reference/petastorm/workers_pool/ventilator.py:55-166
(``ConcurrentVentilator``: background feeding thread, bounded ventilation queue
via processed-item callbacks, per-epoch reshuffle, ``iterations=None`` infinite
epochs, ``completed()``/``reset()``).

Improvement over the reference (SURVEY.md §5 checkpoint gap): the reshuffle RNG
is seedable, making epoch order reproducible when ``random_seed`` is given.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

logger = logging.getLogger(__name__)


class VentilatorBase(object):
    def start(self):
        raise NotImplementedError

    def processed_item(self):
        raise NotImplementedError

    def completed(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError


class ConcurrentVentilator(VentilatorBase):
    """Ventilates ``items_to_ventilate`` (a list of kwargs dicts for
    ``pool.ventilate``) from a background thread.

    :param ventilate_fn: callable(**item) — normally ``pool.ventilate``
    :param items_to_ventilate: list of dicts
    :param iterations: number of passes over the items; ``None`` = infinite
    :param max_ventilation_queue_size: max in-flight (ventilated - processed)
        items; defaults to ``len(items_to_ventilate)``
    :param randomize_item_order: reshuffle item order before each epoch
    :param random_seed: seed for the reshuffle RNG (``None`` = nondeterministic)
    """

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False,
                 random_seed=None):
        if iterations is not None and (not isinstance(iterations, int) or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, got {!r}'.format(iterations))
        self._ventilate_fn = ventilate_fn
        self._items_to_ventilate = list(items_to_ventilate)
        self._iterations_remaining = iterations
        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            if max_ventilation_queue_size is not None
                                            else max(1, len(self._items_to_ventilate)))
        self._randomize_item_order = randomize_item_order
        self._rng = np.random.default_rng(random_seed)

        self._in_flight = 0
        self._in_flight_cv = threading.Condition()
        self._stop_requested = False
        self._completed = len(self._items_to_ventilate) == 0
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        if self._completed:
            return
        self._thread = threading.Thread(target=self._ventilate_loop, daemon=True)
        self._thread.start()

    def processed_item(self):
        """Called by the pool/consumer when one ventilated item finished
        processing; unblocks the feeding thread."""
        with self._in_flight_cv:
            self._in_flight -= 1
            self._in_flight_cv.notify()

    def completed(self):
        """True when no more items will ever be ventilated."""
        return self._completed

    def reset(self):
        """Restart ventilation for the originally requested number of iterations.
        Only valid after the previous run completed (the reference refuses
        mid-epoch reset citing races, reader.py:431-438 — we do too)."""
        if not self._completed:
            raise RuntimeError('Cannot reset ventilator while ventilation is still in progress')
        if self._thread is not None:
            self._thread.join()
        self._completed = len(self._items_to_ventilate) == 0
        self._stop_requested = False
        self._thread = None
        with self._in_flight_cv:
            self._in_flight = 0
        self.start()

    def stop(self):
        self._stop_requested = True
        with self._in_flight_cv:
            self._in_flight_cv.notify_all()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
        self._completed = True

    def _ventilate_loop(self):
        items = list(self._items_to_ventilate)
        while not self._stop_requested:
            if self._randomize_item_order:
                order = self._rng.permutation(len(items))
                items = [items[i] for i in order]
            for item in items:
                with self._in_flight_cv:
                    while (self._in_flight >= self._max_ventilation_queue_size
                           and not self._stop_requested):
                        self._in_flight_cv.wait(timeout=0.1)
                    if self._stop_requested:
                        return
                    self._in_flight += 1
                self._ventilate_fn(**item)
            if self._iterations_remaining is not None:
                self._iterations_remaining -= 1
                if self._iterations_remaining <= 0:
                    break
        self._completed = True
