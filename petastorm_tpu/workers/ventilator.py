"""Ventilator: feeds work items into a pool with bounded in-flight count.

Parity: /root/reference/petastorm/workers_pool/ventilator.py:55-166
(``ConcurrentVentilator``: background feeding thread, bounded ventilation queue
via processed-item callbacks, per-epoch reshuffle, ``iterations=None`` infinite
epochs, ``completed()``/``reset()``).

Improvements over the reference (SURVEY.md §5 checkpoint/reproducibility gaps):
  * the reshuffle RNG is seedable, making epoch order reproducible;
  * read-position checkpointing: every ventilated item carries a ``_seq`` tag,
    the ventilator keeps the set of items not yet *delivered* to the consumer
    (the pool's results-queue reader calls :meth:`mark_delivered` when an item's
    last row is yielded), and :meth:`state_dict`/``resume_state`` capture and
    restore the exact read position — undelivered items plus the unventilated
    tail of the current epoch replay first, then remaining epochs continue from
    the saved RNG state.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict

import numpy as np

from petastorm_tpu import observability as obs

logger = logging.getLogger(__name__)


class VentilatorBase(object):
    def start(self):
        raise NotImplementedError

    def processed_item(self):
        raise NotImplementedError

    def completed(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError


class ConcurrentVentilator(VentilatorBase):
    """Ventilates ``items_to_ventilate`` (a list of kwargs dicts for
    ``pool.ventilate``) from a background thread.

    :param ventilate_fn: callable(**item) — normally ``pool.ventilate``
    :param items_to_ventilate: list of dicts
    :param iterations: number of passes over the items; ``None`` = infinite
    :param max_ventilation_queue_size: max in-flight (ventilated - processed)
        items; defaults to ``len(items_to_ventilate)``
    :param randomize_item_order: reshuffle item order before each epoch
    :param random_seed: seed for the reshuffle RNG (``None`` = nondeterministic)
    :param tag_items: ventilate items with a ``_seq`` kwarg and track delivery
        for checkpointing. Requires ``ventilate_fn`` to understand ``_seq``
        (the worker pools do; plain callables need not). Off by default so the
        standalone ventilate protocol matches the reference's.
    :param resume_state: a dict previously returned by :meth:`state_dict`.
        When given, ``iterations`` is ignored: the saved replay item indices
        are ventilated first (in their original order, no reshuffle), then the
        saved number of remaining epochs run with the saved RNG state.
        ``items_to_ventilate`` must be the same list the state was taken over.
    """

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False,
                 random_seed=None, tag_items=False, resume_state=None):
        if iterations is not None and (not isinstance(iterations, int) or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, got {!r}'.format(iterations))
        self._ventilate_fn = ventilate_fn
        self._items_to_ventilate = list(items_to_ventilate)
        self._requested_iterations = iterations
        self._tag_items = tag_items
        if resume_state is not None and not tag_items:
            raise ValueError('resume_state requires tag_items=True')
        self._randomize_item_order = randomize_item_order
        self._rng = np.random.default_rng(random_seed)

        if resume_state is not None:
            self._replay_indices = list(resume_state['replay_indices'])
            bad = [i for i in self._replay_indices
                   if not 0 <= i < len(self._items_to_ventilate)]
            if bad:
                raise ValueError('resume_state replay indices {} out of range for {} work '
                                 'items'.format(bad, len(self._items_to_ventilate)))
            self._iterations_remaining = resume_state['iterations_remaining']
            if resume_state.get('rng_state') is not None:
                self._rng.bit_generator.state = resume_state['rng_state']
        else:
            self._replay_indices = None
            self._iterations_remaining = iterations

        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            if max_ventilation_queue_size is not None
                                            else max(1, len(self._items_to_ventilate)))

        # trace-mint namespace: '<ns>:<seq>' is each tagged item's trace id
        # (docs/observability.md "trace context"); a fresh nonce per ventilator
        # keeps ids unique across readers/epoch restarts in the same process
        self.trace_ns = os.urandom(4).hex()

        self._in_flight = 0
        self._in_flight_cv = threading.Condition()
        # checkpoint bookkeeping — all guarded by _in_flight_cv's lock. Items
        # are tracked by their index into items_to_ventilate, so state dicts
        # stay small and picklable regardless of item contents (predicates
        # may hold lambdas).
        self._seq = 0
        self._undelivered = OrderedDict()  # seq -> item index (ventilated, not delivered)
        self._epoch_indices = []           # current pass, post-shuffle item indices
        self._epoch_pos = 0                # next position of _epoch_indices to ventilate
        self._epochs_after_current = self._iterations_remaining

        self._stop_requested = False
        self._completed = (len(self._items_to_ventilate) == 0
                           and not self._replay_indices)
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        if self.completed():
            return
        self._thread = threading.Thread(target=self._ventilate_loop, daemon=True)
        self._thread.start()

    def processed_item(self, seq=None):
        """Called by the pool/consumer when one ventilated item finished
        processing; unblocks the feeding thread. ``seq`` is the completed
        item's ventilation seq when the pool knows it (all first-party pools
        do) — this ventilator's budget is global so it ignores it, but the
        :class:`FairShareVentilator` needs it for per-tenant accounting.

        Supervision contract (docs/robustness.md): pools must call this
        EXACTLY ONCE per ventilated item, no matter how many times the item
        was requeued after a worker death or a retried error — a double call
        would over-open the in-flight budget, a missed call would wedge the
        feeding thread and the epoch would never terminate."""
        with self._in_flight_cv:
            self._in_flight -= 1
            self._in_flight_cv.notify()

    def mark_delivered(self, seq):
        """Called by the consumer when the item ventilated with ``_seq == seq``
        has been fully delivered (its last row yielded to the user, or it
        produced no rows). Idempotent; unknown/None seqs are ignored."""
        if seq is None:
            return
        with self._in_flight_cv:
            self._undelivered.pop(seq, None)

    def state_dict(self):
        """Snapshot of the read position, suitable for pickling. Resuming from
        it re-ventilates every item not fully delivered at snapshot time (so
        in-flight row groups are re-read in full), then the unventilated tail
        of the current epoch, then the remaining epochs with the RNG state
        restored (seeded runs continue their original shuffle stream)."""
        if not self._tag_items:
            raise RuntimeError('state_dict() requires tag_items=True (delivery is not tracked '
                               'otherwise, so the read position is unknown)')
        with self._in_flight_cv:
            replay = list(self._undelivered.values())
            replay += self._epoch_indices[self._epoch_pos:]
            return {
                'replay_indices': replay,
                'iterations_remaining': self._epochs_after_current,
                'rng_state': self._rng.bit_generator.state,
            }

    def set_max_queue_size(self, n):
        """Retarget the in-flight item budget at runtime. Used by the
        autotuner when the worker pool grows/shrinks (the budget tracks
        ``workers_count + extra`` exactly as at construction); shrinking
        never cancels already-ventilated items — the feeding thread simply
        waits until completions bring in-flight under the new bound."""
        with self._in_flight_cv:
            self._max_ventilation_queue_size = max(1, int(n))
            self._in_flight_cv.notify_all()

    def upcoming_items(self, max_items):
        """Read-only peek at the next (up to ``max_items``) work items this
        ventilator will emit — the unventilated head of the current epoch, in
        its exact post-shuffle order. Used by the chunk prefetcher
        (``petastorm_tpu.chunkstore.prefetch``) to fetch remote column chunks
        ahead of the workers. Items already ventilated (possibly still being
        processed) are not included; between epochs the list is empty until
        the feeding thread lays out the next epoch's order."""
        with self._in_flight_cv:
            indices = self._epoch_indices[self._epoch_pos:self._epoch_pos + max_items]
            return [self._items_to_ventilate[i] for i in indices]

    def completed(self):
        """True when no more items will ever be ventilated. The flag is
        read/written under ``_in_flight_cv`` like every other piece of
        ventilation state: the feeding thread sets it on exhaustion while
        consumer threads poll it, and the deterministic-schedule explorer
        (``analysis/schedule``) flags the bare-flag protocol this replaced
        as a write/read race."""
        with self._in_flight_cv:
            return self._completed

    def reset(self):
        """Restart ventilation for the originally requested number of iterations.
        Only valid after the previous run completed (the reference refuses
        mid-epoch reset citing races, reader.py:431-438 — we do too)."""
        if not self.completed():
            raise RuntimeError('Cannot reset ventilator while ventilation is still in progress')
        if self._thread is not None:
            self._thread.join()
        self._thread = None
        with self._in_flight_cv:
            self._replay_indices = None
            self._completed = len(self._items_to_ventilate) == 0
            self._stop_requested = False
            self._iterations_remaining = self._requested_iterations
            self._in_flight = 0
            self._undelivered.clear()
            self._epoch_indices = []
            self._epoch_pos = 0
            self._epochs_after_current = self._requested_iterations
        self.start()

    def stop(self):
        # the stop flag joins the rest of the state under _in_flight_cv: the
        # feeding thread re-checks it under the same lock, so the request
        # can never be torn against an in-progress epoch layout
        with self._in_flight_cv:
            self._stop_requested = True
            self._in_flight_cv.notify_all()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
        with self._in_flight_cv:
            self._completed = True

    def _ventilate_loop(self):
        first_pass = True
        while True:
            with self._in_flight_cv:
                if self._stop_requested:
                    break
                if first_pass and self._replay_indices is not None:
                    # resumed run: replay saved items verbatim; does not consume
                    # an iteration (it is the remainder of an interrupted epoch)
                    epoch_indices = list(self._replay_indices)
                    counted = False
                else:
                    if self._iterations_remaining is not None and self._iterations_remaining <= 0:
                        break
                    epoch_indices = list(range(len(self._items_to_ventilate)))
                    if self._randomize_item_order:
                        epoch_indices = [int(i) for i in self._rng.permutation(len(epoch_indices))]
                    counted = True
                self._epoch_indices = epoch_indices
                self._epoch_pos = 0
                if counted and self._iterations_remaining is not None:
                    self._epochs_after_current = self._iterations_remaining - 1
                else:
                    self._epochs_after_current = self._iterations_remaining
            first_pass = False

            for index in epoch_indices:
                with self._in_flight_cv:
                    while (self._in_flight >= self._max_ventilation_queue_size
                           and not self._stop_requested):
                        self._in_flight_cv.wait(timeout=0.1)
                    if self._stop_requested:
                        return
                    self._in_flight += 1
                    self._epoch_pos += 1
                    if self._tag_items:
                        seq = self._seq
                        self._seq += 1
                        self._undelivered[seq] = index
                item = self._items_to_ventilate[index]
                # stage_ventilate_* counters + (at spans level) one event per
                # dispatched work item, on the ventilator thread's track
                if self._tag_items:
                    # mint the item's TraceContext: the ventilate span becomes
                    # the virtual root's first child, and pool.ventilate
                    # (running inside the block) captures the context so it
                    # travels to workers on the existing channels
                    with obs.mint_trace(self.trace_ns, seq):
                        with obs.stage('ventilate', cat='ventilator'):
                            self._ventilate_fn(**dict(item, _seq=seq))
                else:
                    with obs.stage('ventilate', cat='ventilator'):
                        self._ventilate_fn(**item)

            with self._in_flight_cv:
                if counted and self._iterations_remaining is not None:
                    self._iterations_remaining -= 1
        with self._in_flight_cv:
            self._completed = True


class _TenantQueue(object):
    """One tenant's item stream inside a :class:`FairShareVentilator`: its
    items, remaining epochs, weight, in-flight budget, and counters. All
    mutation happens under the ventilator's condition lock."""

    __slots__ = ('tenant_id', 'items', 'iterations_remaining', 'weight',
                 'max_in_flight', 'in_flight', 'dispatched', 'completed',
                 'epoch_indices', 'epoch_pos', 'rng', 'shuffle', 'credits',
                 'finished', 'removed')

    def __init__(self, tenant_id, items, iterations, weight, max_in_flight,
                 shuffle, seed):
        self.tenant_id = tenant_id
        self.items = list(items)
        self.iterations_remaining = iterations
        self.weight = max(1, int(weight))
        self.max_in_flight = max(1, int(max_in_flight))
        self.in_flight = 0
        self.dispatched = 0
        self.completed = 0
        self.epoch_indices = []
        self.epoch_pos = 0
        self.rng = np.random.default_rng(seed)
        self.shuffle = shuffle
        self.credits = 0
        self.finished = not self.items or iterations == 0
        self.removed = False

    def _lay_out_epoch(self):
        """Start the next epoch's order, or mark the stream finished."""
        if self.iterations_remaining is not None:
            if self.iterations_remaining <= 0:
                self.finished = True
                return False
            self.iterations_remaining -= 1
        order = list(range(len(self.items)))
        if self.shuffle:
            order = [int(i) for i in self.rng.permutation(len(order))]
        self.epoch_indices = order
        self.epoch_pos = 0
        return True

    def next_item(self):
        """The next item to dispatch, or None when the stream is exhausted.
        Does NOT check the in-flight budget (the scheduler does)."""
        if self.finished:
            return None
        if self.epoch_pos >= len(self.epoch_indices):
            if not self._lay_out_epoch():
                return None
        item = self.items[self.epoch_indices[self.epoch_pos]]
        self.epoch_pos += 1
        return item

    def exhausted(self):
        """No further dispatches will ever happen for this tenant."""
        if self.removed:
            return True
        if not self.finished:
            if self.epoch_pos < len(self.epoch_indices):
                return False
            if self.iterations_remaining is None or self.iterations_remaining > 0:
                return False
        return True

    def stats(self):
        return {'weight': self.weight, 'max_in_flight': self.max_in_flight,
                'in_flight': self.in_flight, 'dispatched': self.dispatched,
                'completed': self.completed, 'finished': self.finished,
                'removed': self.removed}


class FairShareVentilator(VentilatorBase):
    """Multiplexes MANY tenants' item streams onto ONE pool with weighted
    fair-share scheduling — the serve daemon's broker half (``docs/serve.md``).

    Each tenant registers an item list (row groups of its stream), an epoch
    count, a scheduling ``weight`` and a per-tenant ``max_in_flight`` budget
    (admission control: one tenant can never occupy more pool slots than its
    budget, no matter how fast it drains results). Dispatch is starvation-free
    weighted round-robin: every scheduling cycle refills each eligible
    tenant's credits to its weight and then drains credits cyclically, so a
    weight-2 tenant gets two dispatches per cycle to a weight-1 tenant's one,
    and a tenant is never skipped while it has credits, backlog, and budget
    headroom.

    Every dispatched item is tagged with a globally unique ``_seq`` and the
    tenant's ``stream_id`` kwarg; pools report completions back through
    :meth:`processed_item(seq)` which resolves the owning tenant for budget
    release and per-tenant epoch-termination detection (``on_tenant_done``
    fires exactly once per tenant, when its last in-flight item completes
    after its final epoch was fully dispatched).

    Unlike :class:`ConcurrentVentilator` this ventilator is LONG-LIVED: it
    completes only when stopped, tenants attach/detach at runtime, and
    removing a tenant mid-epoch simply stops feeding it (in-flight items drain
    normally; their completions release the budget but no done callback
    fires)."""

    def __init__(self, ventilate_fn, on_tenant_done=None):
        self._ventilate_fn = ventilate_fn
        self._on_tenant_done = on_tenant_done
        # trace-mint namespace; the serve daemon hands it to clients in the
        # attach reply so they can derive each frame's trace root from the
        # seq already present in the ring header (zero extra wire bytes)
        self.trace_ns = os.urandom(4).hex()
        self._cv = threading.Condition()
        self._tenants = {}          # tenant_id -> _TenantQueue
        self._order = []            # round-robin order of tenant ids
        self._final_stats = {}      # drained tenants' last counters (bounded)
        self._seq = 0
        self._seq_tenant = {}       # seq -> tenant_id (live dispatches only)
        self._stop_requested = False
        self._completed = False
        self._thread = None

    # -- tenant lifecycle ----------------------------------------------------

    def add_tenant(self, tenant_id, items, iterations=1, weight=1,
                   max_in_flight=2, shuffle=False, seed=None):
        """Register a tenant's stream; dispatching starts immediately (the
        feeding thread wakes on the next cycle). Safe mid-run."""
        if iterations is not None and (not isinstance(iterations, int) or iterations < 0):
            raise ValueError('iterations must be a non-negative int or None')
        with self._cv:
            if tenant_id in self._tenants:
                raise ValueError('tenant {!r} already registered'.format(tenant_id))
            tq = _TenantQueue(tenant_id, items, iterations, weight,
                              max_in_flight, shuffle, seed)
            if not tq.finished:
                self._tenants[tenant_id] = tq
                self._order.append(tenant_id)
            self._cv.notify_all()
        if tq.finished:
            # zero items / zero epochs: terminate the stream immediately
            self._fire_done(tenant_id)

    def remove_tenant(self, tenant_id):
        """Stop feeding a tenant mid-run. In-flight items drain normally
        (their completions release pool budget); no done callback fires."""
        with self._cv:
            tq = self._tenants.get(tenant_id)
            if tq is None:
                return False
            tq.removed = True
            tq.finished = True
            if tq.in_flight == 0:
                self._forget(tenant_id)
            self._cv.notify_all()
        return True

    def _forget(self, tenant_id):
        """Drop a fully-drained tenant's bookkeeping, retaining its final
        counters for diagnostics (fair-share occupancy must survive stream
        completion). Caller holds _cv."""
        tq = self._tenants.pop(tenant_id, None)  # noqa: PT100 - every caller holds _cv
        if tq is not None:
            self._final_stats[tenant_id] = tq.stats()  # noqa: PT100 - caller holds _cv
            while len(self._final_stats) > 64:  # bounded history
                self._final_stats.pop(next(iter(self._final_stats)))  # noqa: PT100 - caller holds _cv
        if tenant_id in self._order:
            self._order.remove(tenant_id)  # noqa: PT100 - every caller holds _cv

    def tenant_stats(self):
        """Per-tenant scheduling/occupancy counters (fair-share evidence for
        diagnostics; docs/serve.md) — live tenants plus the retained final
        counters of recently drained ones."""
        with self._cv:
            out = dict(self._final_stats)
            out.update({tid: tq.stats() for tid, tq in self._tenants.items()})
            return out

    def set_tenant_weight(self, tenant_id, weight):
        """Retune a tenant's fair share at runtime (takes effect at the next
        credit refill). True when the tenant is still registered."""
        with self._cv:
            tq = self._tenants.get(tenant_id)
            if tq is None:
                return False
            tq.weight = max(1, int(weight))
            return True

    def tenant_of_seq(self, seq):
        """Owning tenant of a live dispatch seq (None once completed)."""
        with self._cv:
            return self._seq_tenant.get(seq)

    # -- VentilatorBase ------------------------------------------------------

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        self._thread = threading.Thread(target=self._ventilate_loop, daemon=True,
                                        name='pstpu-fairshare-ventilator')
        self._thread.start()

    def processed_item(self, seq=None):
        """Pool completion callback: releases the owning tenant's in-flight
        budget and fires ``on_tenant_done`` when its stream fully drains."""
        done_tenant = None
        with self._cv:
            tenant_id = self._seq_tenant.pop(seq, None)
            tq = self._tenants.get(tenant_id) if tenant_id is not None else None
            if tq is not None:
                tq.in_flight -= 1
                tq.completed += 1
                if tq.exhausted() and tq.in_flight == 0:
                    if not tq.removed:
                        done_tenant = tenant_id
                    self._forget(tenant_id)
            self._cv.notify_all()
        if done_tenant is not None:
            self._fire_done(done_tenant)

    def _fire_done(self, tenant_id):
        if self._on_tenant_done is not None:
            self._on_tenant_done(tenant_id)

    def completed(self):
        """Long-lived: only a stop completes this ventilator. Read under
        ``_cv`` — the flag protocol matches :class:`ConcurrentVentilator`."""
        with self._cv:
            return self._completed

    def stop(self):
        with self._cv:
            self._stop_requested = True
            self._cv.notify_all()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
        with self._cv:
            self._completed = True

    def upcoming_items(self, max_items):
        """Merged read-only peek at the next items across tenants (for the
        chunk prefetcher): interleaves each tenant's unventilated head in
        round-robin order."""
        with self._cv:
            heads = []
            for tid in self._order:
                tq = self._tenants[tid]
                if tq.finished:
                    continue
                idxs = tq.epoch_indices[tq.epoch_pos:tq.epoch_pos + max_items]
                heads.append([tq.items[i] for i in idxs])
            out = []
            for layer in zip(*heads) if heads else ():
                out.extend(layer)
                if len(out) >= max_items:
                    break
            return out[:max_items]

    # -- the scheduler -------------------------------------------------------

    def _pick_next(self):
        """Under the lock: the next (tenant, item, seq) to dispatch by
        weighted round-robin, or None when nothing is eligible. Refills
        credits when every backlogged tenant is out of them, so weights shape
        shares without ever starving anyone."""
        for _refill in (False, True):
            if _refill:
                eligible = [self._tenants[tid] for tid in self._order
                            if not self._tenants[tid].finished
                            and self._tenants[tid].in_flight < self._tenants[tid].max_in_flight]
                if not eligible:
                    return None
                for tq in eligible:
                    tq.credits = tq.weight
            for tid in list(self._order):
                tq = self._tenants[tid]
                if (tq.finished or tq.credits <= 0
                        or tq.in_flight >= tq.max_in_flight):
                    continue
                item = tq.next_item()
                if item is None:
                    continue
                tq.credits -= 1
                tq.in_flight += 1
                tq.dispatched += 1
                seq = self._seq
                self._seq += 1
                self._seq_tenant[seq] = tid  # noqa: PT100 - _pick_next runs under _cv
                # rotate: the tenant goes to the back so equal-credit tenants
                # alternate instead of one draining its whole credit run
                self._order.remove(tid)  # noqa: PT100 - _pick_next runs under _cv
                self._order.append(tid)  # noqa: PT100 - _pick_next runs under _cv
                return tq, item, seq
        return None

    def _ventilate_loop(self):
        while True:
            with self._cv:
                while not self._stop_requested:
                    picked = self._pick_next()
                    if picked is not None:
                        break
                    self._cv.wait(timeout=0.1)
                if self._stop_requested:
                    return
                tq, item, seq = picked
            # mint: seq is globally unique here, so '<ns>:<seq>' uniquely
            # names the item across every tenant sharing this broker
            with obs.mint_trace(self.trace_ns, seq):
                with obs.stage('ventilate', cat='ventilator'):
                    self._ventilate_fn(**dict(item, _seq=seq))
