"""Ventilator: feeds work items into a pool with bounded in-flight count.

Parity: /root/reference/petastorm/workers_pool/ventilator.py:55-166
(``ConcurrentVentilator``: background feeding thread, bounded ventilation queue
via processed-item callbacks, per-epoch reshuffle, ``iterations=None`` infinite
epochs, ``completed()``/``reset()``).

Improvements over the reference (SURVEY.md §5 checkpoint/reproducibility gaps):
  * the reshuffle RNG is seedable, making epoch order reproducible;
  * read-position checkpointing: every ventilated item carries a ``_seq`` tag,
    the ventilator keeps the set of items not yet *delivered* to the consumer
    (the pool's results-queue reader calls :meth:`mark_delivered` when an item's
    last row is yielded), and :meth:`state_dict`/``resume_state`` capture and
    restore the exact read position — undelivered items plus the unventilated
    tail of the current epoch replay first, then remaining epochs continue from
    the saved RNG state.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict

import numpy as np

from petastorm_tpu import observability as obs

logger = logging.getLogger(__name__)


class VentilatorBase(object):
    def start(self):
        raise NotImplementedError

    def processed_item(self):
        raise NotImplementedError

    def completed(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError


class ConcurrentVentilator(VentilatorBase):
    """Ventilates ``items_to_ventilate`` (a list of kwargs dicts for
    ``pool.ventilate``) from a background thread.

    :param ventilate_fn: callable(**item) — normally ``pool.ventilate``
    :param items_to_ventilate: list of dicts
    :param iterations: number of passes over the items; ``None`` = infinite
    :param max_ventilation_queue_size: max in-flight (ventilated - processed)
        items; defaults to ``len(items_to_ventilate)``
    :param randomize_item_order: reshuffle item order before each epoch
    :param random_seed: seed for the reshuffle RNG (``None`` = nondeterministic)
    :param tag_items: ventilate items with a ``_seq`` kwarg and track delivery
        for checkpointing. Requires ``ventilate_fn`` to understand ``_seq``
        (the worker pools do; plain callables need not). Off by default so the
        standalone ventilate protocol matches the reference's.
    :param resume_state: a dict previously returned by :meth:`state_dict`.
        When given, ``iterations`` is ignored: the saved replay item indices
        are ventilated first (in their original order, no reshuffle), then the
        saved number of remaining epochs run with the saved RNG state.
        ``items_to_ventilate`` must be the same list the state was taken over.
    """

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False,
                 random_seed=None, tag_items=False, resume_state=None):
        if iterations is not None and (not isinstance(iterations, int) or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, got {!r}'.format(iterations))
        self._ventilate_fn = ventilate_fn
        self._items_to_ventilate = list(items_to_ventilate)
        self._requested_iterations = iterations
        self._tag_items = tag_items
        if resume_state is not None and not tag_items:
            raise ValueError('resume_state requires tag_items=True')
        self._randomize_item_order = randomize_item_order
        self._rng = np.random.default_rng(random_seed)

        if resume_state is not None:
            self._replay_indices = list(resume_state['replay_indices'])
            bad = [i for i in self._replay_indices
                   if not 0 <= i < len(self._items_to_ventilate)]
            if bad:
                raise ValueError('resume_state replay indices {} out of range for {} work '
                                 'items'.format(bad, len(self._items_to_ventilate)))
            self._iterations_remaining = resume_state['iterations_remaining']
            if resume_state.get('rng_state') is not None:
                self._rng.bit_generator.state = resume_state['rng_state']
        else:
            self._replay_indices = None
            self._iterations_remaining = iterations

        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            if max_ventilation_queue_size is not None
                                            else max(1, len(self._items_to_ventilate)))

        self._in_flight = 0
        self._in_flight_cv = threading.Condition()
        # checkpoint bookkeeping — all guarded by _in_flight_cv's lock. Items
        # are tracked by their index into items_to_ventilate, so state dicts
        # stay small and picklable regardless of item contents (predicates
        # may hold lambdas).
        self._seq = 0
        self._undelivered = OrderedDict()  # seq -> item index (ventilated, not delivered)
        self._epoch_indices = []           # current pass, post-shuffle item indices
        self._epoch_pos = 0                # next position of _epoch_indices to ventilate
        self._epochs_after_current = self._iterations_remaining

        self._stop_requested = False
        self._completed = (len(self._items_to_ventilate) == 0
                           and not self._replay_indices)
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        if self._completed:
            return
        self._thread = threading.Thread(target=self._ventilate_loop, daemon=True)
        self._thread.start()

    def processed_item(self):
        """Called by the pool/consumer when one ventilated item finished
        processing; unblocks the feeding thread.

        Supervision contract (docs/robustness.md): pools must call this
        EXACTLY ONCE per ventilated item, no matter how many times the item
        was requeued after a worker death or a retried error — a double call
        would over-open the in-flight budget, a missed call would wedge the
        feeding thread and the epoch would never terminate."""
        with self._in_flight_cv:
            self._in_flight -= 1
            self._in_flight_cv.notify()

    def mark_delivered(self, seq):
        """Called by the consumer when the item ventilated with ``_seq == seq``
        has been fully delivered (its last row yielded to the user, or it
        produced no rows). Idempotent; unknown/None seqs are ignored."""
        if seq is None:
            return
        with self._in_flight_cv:
            self._undelivered.pop(seq, None)

    def state_dict(self):
        """Snapshot of the read position, suitable for pickling. Resuming from
        it re-ventilates every item not fully delivered at snapshot time (so
        in-flight row groups are re-read in full), then the unventilated tail
        of the current epoch, then the remaining epochs with the RNG state
        restored (seeded runs continue their original shuffle stream)."""
        if not self._tag_items:
            raise RuntimeError('state_dict() requires tag_items=True (delivery is not tracked '
                               'otherwise, so the read position is unknown)')
        with self._in_flight_cv:
            replay = list(self._undelivered.values())
            replay += self._epoch_indices[self._epoch_pos:]
            return {
                'replay_indices': replay,
                'iterations_remaining': self._epochs_after_current,
                'rng_state': self._rng.bit_generator.state,
            }

    def set_max_queue_size(self, n):
        """Retarget the in-flight item budget at runtime. Used by the
        autotuner when the worker pool grows/shrinks (the budget tracks
        ``workers_count + extra`` exactly as at construction); shrinking
        never cancels already-ventilated items — the feeding thread simply
        waits until completions bring in-flight under the new bound."""
        with self._in_flight_cv:
            self._max_ventilation_queue_size = max(1, int(n))
            self._in_flight_cv.notify_all()

    def upcoming_items(self, max_items):
        """Read-only peek at the next (up to ``max_items``) work items this
        ventilator will emit — the unventilated head of the current epoch, in
        its exact post-shuffle order. Used by the chunk prefetcher
        (``petastorm_tpu.chunkstore.prefetch``) to fetch remote column chunks
        ahead of the workers. Items already ventilated (possibly still being
        processed) are not included; between epochs the list is empty until
        the feeding thread lays out the next epoch's order."""
        with self._in_flight_cv:
            indices = self._epoch_indices[self._epoch_pos:self._epoch_pos + max_items]
            return [self._items_to_ventilate[i] for i in indices]

    def completed(self):
        """True when no more items will ever be ventilated."""
        return self._completed

    def reset(self):
        """Restart ventilation for the originally requested number of iterations.
        Only valid after the previous run completed (the reference refuses
        mid-epoch reset citing races, reader.py:431-438 — we do too)."""
        if not self._completed:
            raise RuntimeError('Cannot reset ventilator while ventilation is still in progress')
        if self._thread is not None:
            self._thread.join()
        self._replay_indices = None
        self._completed = len(self._items_to_ventilate) == 0
        self._stop_requested = False
        self._thread = None
        with self._in_flight_cv:
            self._iterations_remaining = self._requested_iterations
            self._in_flight = 0
            self._undelivered.clear()
            self._epoch_indices = []
            self._epoch_pos = 0
            self._epochs_after_current = self._requested_iterations
        self.start()

    def stop(self):
        self._stop_requested = True
        with self._in_flight_cv:
            self._in_flight_cv.notify_all()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
        self._completed = True

    def _ventilate_loop(self):
        first_pass = True
        while not self._stop_requested:
            with self._in_flight_cv:
                if first_pass and self._replay_indices is not None:
                    # resumed run: replay saved items verbatim; does not consume
                    # an iteration (it is the remainder of an interrupted epoch)
                    epoch_indices = list(self._replay_indices)
                    counted = False
                else:
                    if self._iterations_remaining is not None and self._iterations_remaining <= 0:
                        break
                    epoch_indices = list(range(len(self._items_to_ventilate)))
                    if self._randomize_item_order:
                        epoch_indices = [int(i) for i in self._rng.permutation(len(epoch_indices))]
                    counted = True
                self._epoch_indices = epoch_indices
                self._epoch_pos = 0
                if counted and self._iterations_remaining is not None:
                    self._epochs_after_current = self._iterations_remaining - 1
                else:
                    self._epochs_after_current = self._iterations_remaining
            first_pass = False

            for index in epoch_indices:
                with self._in_flight_cv:
                    while (self._in_flight >= self._max_ventilation_queue_size
                           and not self._stop_requested):
                        self._in_flight_cv.wait(timeout=0.1)
                    if self._stop_requested:
                        return
                    self._in_flight += 1
                    self._epoch_pos += 1
                    if self._tag_items:
                        seq = self._seq
                        self._seq += 1
                        self._undelivered[seq] = index
                item = self._items_to_ventilate[index]
                # stage_ventilate_* counters + (at spans level) one event per
                # dispatched work item, on the ventilator thread's track
                with obs.stage('ventilate', cat='ventilator'):
                    if self._tag_items:
                        self._ventilate_fn(**dict(item, _seq=seq))
                    else:
                        self._ventilate_fn(**item)

            with self._in_flight_cv:
                if counted and self._iterations_remaining is not None:
                    self._iterations_remaining -= 1
        self._completed = True
