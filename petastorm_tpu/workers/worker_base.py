"""Worker protocol shared by all pool implementations.

Parity: /root/reference/petastorm/workers_pool/worker_base.py:18-35 and the
sentinels in workers_pool/__init__.py:16-26.
"""

from __future__ import annotations


class EmptyResultError(Exception):
    """Raised by ``pool.get_results()`` when all ventilated work has been
    processed and no further results will arrive."""


class TimeoutWaitingForResultError(Exception):
    """Raised when a pool timed out waiting for worker results."""


class WorkerTerminationRequested(Exception):
    """Raised inside a worker's ``process`` by ``publish`` when the pool is
    stopping, to unwind the worker promptly."""


class WorkerBase(object):
    """A worker processes one ventilated item per ``process`` call and publishes
    zero or more results via ``publish_func``.

    :param worker_id: ordinal of this worker in the pool
    :param publish_func: callable(result) delivering a result to the consumer
    :param args: pool-wide setup arguments (must be picklable for process pools)
    """

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def process(self, *args, **kwargs):
        """Handle one ventilated item."""
        raise NotImplementedError

    def publish(self, data):
        self.publish_func(data)

    def shutdown(self):
        """Called once when the pool stops; release worker-held resources."""
