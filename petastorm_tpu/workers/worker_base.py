"""Worker protocol shared by all pool implementations.

Parity: /root/reference/petastorm/workers_pool/worker_base.py:18-35 and the
sentinels in workers_pool/__init__.py:16-26.

The worker-plane exceptions are defined in :mod:`petastorm_tpu.errors` (rooted
at ``PetastormTpuError``); the names below are kept as import aliases because
this module was their historical home.
"""

from __future__ import annotations

from petastorm_tpu.errors import (EmptyResultError,  # noqa: F401 - compat aliases
                                  TimeoutWaitingForResultError,
                                  WorkerTerminationRequested)


class WorkerBase(object):
    """A worker processes one ventilated item per ``process`` call and publishes
    zero or more results via ``publish_func``.

    :param worker_id: ordinal of this worker in the pool
    :param publish_func: callable(result) delivering a result to the consumer
    :param args: pool-wide setup arguments (must be picklable for process pools)
    """

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def process(self, *args, **kwargs):
        """Handle one ventilated item."""
        raise NotImplementedError

    def publish(self, data):
        self.publish_func(data)

    def shutdown(self):
        """Called once when the pool stops; release worker-held resources."""
