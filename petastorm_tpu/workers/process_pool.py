"""Process pool: spawned worker processes, shm-ring or ZMQ star topology.

Parity: /root/reference/petastorm/workers_pool/process_pool.py —
main PUSH -> workers (ventilate), main PUB -> workers (control),
workers -> main (results) (:52-74); spawn not fork (:15-17);
startup handshake (:208-214); orphaned-worker suicide via a main-pid monitor
thread (:324-331); slow-joiner-safe shutdown rebroadcasting FINISHED (:287-304);
pluggable payload serializers; ``diagnostics`` (:306-314).

TPU-first departure: the high-bandwidth worker->main results path defaults to
the first-party C++ shared-memory SPSC ring (native/shm_ring.cpp) — one memcpy
in, one out, no socket syscalls — with the reference-style ZMQ PULL as the
fallback (``transport='zmq'``). Ventilation and control stay on ZMQ (ipc://
endpoints in a private temp dir): they are low-bandwidth and need fan-out/
fan-in semantics the ring does not provide.

Note: workers are spawned, so (as with any ``multiprocessing`` spawn user)
scripts creating a ProcessPool at module level must guard the pool-creating code
with ``if __name__ == '__main__':`` — the child re-imports ``__main__``.
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import pickle
import shutil
import struct
import sys
import tempfile
import threading
import time
import uuid

import zmq

from petastorm_tpu import observability as obs
from petastorm_tpu.serializers import PickleSerializer
from petastorm_tpu.workers.worker_base import EmptyResultError, TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

_CONTROL_FINISHED = b'FINISHED'
_STARTED, _DATA, _DONE, _ERROR, _BLOB = b'S', b'D', b'F', b'E', b'B'
#: telemetry piggyback on the results channel: a worker ships its cumulative
#: metrics snapshot (and, at spans level, its drained trace events) after each
#: completed item — the same route the payloads travel, so ordering guarantees
#: the final snapshot arrives before the consumer sees the pool as drained
_METRICS = b'M'

_WORKER_STARTUP_TIMEOUT_S = 30
_DEFAULT_RESULTS_HWM = 50
_DEFAULT_RING_BYTES = 64 << 20
#: payloads at least this large ride the per-message /dev/shm blob sidechannel
#: (when the serializer supports single-copy serialize_into): the worker writes
#: the message straight into an mmapped tmpfs file and only the file name
#: crosses the ring/zmq transport — 1 data copy end-to-end instead of 3
#: (serialize join + ring in + ring out). Small payloads keep the low-latency
#: in-band path.
_DEFAULT_BLOB_THRESHOLD = 1 << 20
#: per-POOL bound on UNCONSUMED blob bytes (workers share the run's blob dir,
#: and blobs are unlinked on read, so the dir size is the live backlog) — the
#: byte-backpressure analog of the ring's capacity: workers whose consumer
#: lags block instead of parking unbounded row groups in tmpfs. A single
#: over-budget blob is still allowed through (mirroring the ring's
#: one-payload-must-fit invariant).
_BLOB_BUDGET_BYTES = 256 << 20


#: minimum age before a blob dir with a dead/unparseable owner pid may be
#: reaped — protects a just-created dir whose owner the pid probe cannot see
#: (e.g. a different PID namespace sharing /dev/shm)
_BLOB_SWEEP_GRACE_S = 600


def _sweep_stale_blob_dirs(shm_root):
    """Reap ``pstpu_blobs_<pid>_*`` dirs whose owning process is gone AND whose
    mtime is older than a grace period: blobs from a hard-killed run persist in
    tmpfs forever (no kernel reclaim), and enough of them would silently
    self-disable the sidechannel for every later pool via the headroom check.
    Dirs without a parseable pid are treated as dead-owner (nothing alive can
    own them across a restart) but still get the mtime grace. Best-effort: any
    per-entry error skips that entry, never pool startup."""
    try:
        entries = list(os.scandir(shm_root))
    except OSError:
        return
    now = time.time()
    for entry in entries:
        if not entry.name.startswith('pstpu_blobs_'):
            continue
        try:
            owner_alive = False
            parts = entry.name.split('_')
            # <= 10 digits: anything longer overflows a C pid_t (os.kill would
            # raise OverflowError) and is treated as no-parseable-owner instead
            if (len(parts) >= 3 and parts[2].isascii() and parts[2].isdigit()
                    and len(parts[2]) <= 10):
                pid = int(parts[2])
                if pid == os.getpid():
                    continue
                try:
                    os.kill(pid, 0)  # signal 0: existence probe only
                    owner_alive = True
                except ProcessLookupError:
                    owner_alive = False
                except PermissionError:
                    owner_alive = True  # exists, owned by someone else
            if not owner_alive and now - entry.stat().st_mtime >= _BLOB_SWEEP_GRACE_S:
                shutil.rmtree(entry.path, ignore_errors=True)
        except (OSError, OverflowError, ValueError):
            # e.g. os.kill OverflowError on an absurd digit string: skip the
            # entry, never pool startup
            continue


def _read_blob(path):
    """Map a blob file copy-on-write and unlink it: the returned memoryview's
    consumers (numpy views) keep the mapping — and thus the pages — alive; the
    name disappears immediately, so nothing leaks even if deserialization
    fails. ACCESS_COPY gives WRITABLE views without an upfront copy — the
    uniform process-pool contract (the shm ring's per-message bytearray is
    writable too, and the zmq fallback copies to match): writability must not
    depend on which channel a payload happened to ride."""
    import mmap
    with open(path, 'rb') as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
    os.unlink(path)
    return memoryview(mm)


def _ring_header(kind, seq):
    """Ring message framing: kind byte + little-endian int64 seq (-1 = None),
    then the payload; header and payload are gather-written as one message."""
    return kind + struct.pack('<q', -1 if seq is None else seq)


def _ring_unpack(view):
    """(kind, seq, payload_view) from a message memoryview — the payload stays
    a zero-copy view handed straight to the deserializer."""
    seq = struct.unpack_from('<q', view, 1)[0]
    return bytes(view[0:1]), (None if seq < 0 else seq), view[9:]


class ProcessPool(object):
    def __init__(self, workers_count, results_queue_size=_DEFAULT_RESULTS_HWM, serializer=None,
                 results_timeout_s=None, transport=None, ring_bytes=_DEFAULT_RING_BYTES,
                 blob_threshold_bytes=_DEFAULT_BLOB_THRESHOLD):
        """``results_timeout_s``: raise if no worker message arrives within this
        many seconds (None = block indefinitely, matching ThreadPool).
        ``transport``: 'shm' (first-party C++ shared-memory rings) | 'zmq' |
        None = shm when the native library is available, else zmq.
        ``ring_bytes``: per-worker ring capacity for the shm transport; one
        serialized row-group payload must fit.
        ``blob_threshold_bytes``: payloads >= this ride the single-copy
        /dev/shm blob sidechannel when the serializer supports
        ``serialize_into`` (0 disables)."""
        self._workers_count = workers_count
        self._results_hwm = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._results_timeout_s = results_timeout_s
        if transport is None:
            from petastorm_tpu.native import shm_ring
            transport = 'shm' if shm_ring.is_available() else 'zmq'
        if transport not in ('shm', 'zmq'):
            raise ValueError("transport must be 'shm', 'zmq' or None, got {!r}".format(transport))
        self._transport = transport
        self._ring_bytes = ring_bytes
        self._blob_threshold = blob_threshold_bytes
        self._blob_dir = None
        self._rings = []
        self._context = None
        self._processes = []
        self._ventilator = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._stopped = False
        self._ipc_dir = None
        # The C++ ring is strictly single-consumer; this lock serializes the
        # get_results() poll loop against the join() drain so two threads never
        # race pstpu_ring_read on the same ring.
        self._ring_lock = threading.Lock()
        # checkpoint plumbing (see thread_pool.py): messages carry the item seq
        self.last_result_seq = None
        self.done_callback = None
        # pid -> latest cumulative metrics snapshot from that worker process
        # (consumer thread only; merged by Reader.diagnostics)
        self._telemetry_by_pid = {}

    @property
    def transport(self):
        return self._transport

    def _create_rings(self, ring_names):
        from petastorm_tpu.native.shm_ring import ShmRing
        # Rings smaller than requested would break the "one serialized
        # row-group payload must fit" invariant mid-run, so when /dev/shm
        # cannot hold full-size rings (docker often caps it at 64MB) we bail
        # out here and let the caller fall back to zmq instead.
        try:
            st = os.statvfs('/dev/shm')
            avail = st.f_bavail * st.f_frsize
        except OSError:
            # statvfs unavailable: proceed; the pre-faulting create still
            # surfaces exhaustion as a catchable error
            avail = None
        if avail is not None and self._ring_bytes * self._workers_count > avail * 0.9:
            raise OSError(
                '/dev/shm has {} bytes free; {} rings of {} bytes will not fit'.format(
                    avail, self._workers_count, self._ring_bytes))
        run_id = uuid.uuid4().hex[:12]
        for worker_id in range(self._workers_count):
            name = '/pstpu_{}_{}_{}'.format(os.getpid(), run_id, worker_id)
            self._rings.append(ShmRing.create(name, self._ring_bytes))
            ring_names[worker_id] = name

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._processes:
            raise RuntimeError('Pool already started')
        self._context = zmq.Context()
        self._ipc_dir = tempfile.mkdtemp(prefix='pstpu_pool_')
        vent_addr = 'ipc://' + os.path.join(self._ipc_dir, 'vent')
        result_addr = 'ipc://' + os.path.join(self._ipc_dir, 'result')
        control_addr = 'ipc://' + os.path.join(self._ipc_dir, 'control')

        self._ventilator_send = self._context.socket(zmq.PUSH)
        self._ventilator_send.setsockopt(zmq.LINGER, 0)
        self._ventilator_send.bind(vent_addr)
        self._control_send = self._context.socket(zmq.PUB)
        self._control_send.setsockopt(zmq.LINGER, 0)
        self._control_send.bind(control_addr)

        ring_names = [None] * self._workers_count
        self._results_receive = None
        if self._transport == 'shm':
            try:
                self._create_rings(ring_names)
            except OSError as e:
                # /dev/shm too small for the requested rings (surfaced as a
                # catchable error by the pre-faulting create, not SIGBUS):
                # degrade to the zmq transport rather than dying later.
                logger.warning('shm ring allocation failed (%s); falling back to zmq transport', e)
                for ring in self._rings:
                    ring.close()
                self._rings = []
                ring_names = [None] * self._workers_count
                self._transport = 'zmq'
        if self._transport == 'zmq':
            self._results_receive = self._context.socket(zmq.PULL)
            self._results_receive.setsockopt(zmq.RCVHWM, self._results_hwm)
            self._results_receive.bind(result_addr)

        # per-run /dev/shm blob dir for the large-payload sidechannel: only when
        # the serializer can route payloads in one pass and tmpfs has at least
        # token headroom (workers additionally self-disable after persistent
        # ENOSPC — the capacity can change under us at runtime)
        if (self._blob_threshold and hasattr(self._serializer, 'serialize_parts')
                and os.path.isdir('/dev/shm')):
            _sweep_stale_blob_dirs('/dev/shm')
            try:
                st = os.statvfs('/dev/shm')
                if st.f_bavail * st.f_frsize >= 4 * self._blob_threshold:
                    # owner pid is encoded in the name so a future pool start can
                    # reap dirs orphaned by a hard-killed process (tmpfs never
                    # reclaims them on its own)
                    self._blob_dir = tempfile.mkdtemp(
                        prefix='pstpu_blobs_{}_'.format(os.getpid()), dir='/dev/shm')
            except OSError:
                self._blob_dir = None

        # spawn (NOT fork): forked children inherit locked mutexes/threads from
        # Arrow, JAX, etc. (reference process_pool.py:15-17 for the JVM analog)
        ctx = multiprocessing.get_context('spawn')
        setup_blob = pickle.dumps((worker_class, worker_setup_args, self._serializer),
                                  protocol=pickle.HIGHEST_PROTOCOL)
        for worker_id in range(self._workers_count):
            p = ctx.Process(
                target=_worker_bootstrap,
                args=(worker_id, os.getpid(), setup_blob, vent_addr, result_addr, control_addr,
                      self._results_hwm, ring_names[worker_id],
                      self._blob_dir, self._blob_threshold, self._workers_count),
                daemon=True)
            p.start()
            self._processes.append(p)

        # startup handshake: wait until every worker connected and reported in
        deadline = time.monotonic() + _WORKER_STARTUP_TIMEOUT_S
        started = 0
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop(); self.join()
                raise TimeoutWaitingForResultError(
                    'Only {} of {} workers started within {}s'.format(
                        started, self._workers_count, _WORKER_STARTUP_TIMEOUT_S))
            msg = self._poll_message(100)
            if msg is not None and msg[0] == _STARTED:
                started += 1

        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def _poll_message(self, timeout_ms):
        """Next (kind, seq, payload_bytes) from the results transport, or None
        after ``timeout_ms``. shm: round-robin over the per-worker rings."""
        if self._transport == 'zmq':
            if not self._results_receive.poll(timeout_ms):
                return None
            kind, seq_bytes, payload = self._results_receive.recv_multipart()
            if kind == _DATA:
                # bytes are immutable and would make the deserializer's views
                # read-only; the ring and blob channels hand out writable
                # views, and the contract must not depend on the transport
                payload = bytearray(payload)
            return kind, (int(seq_bytes) if seq_bytes else None), payload
        deadline = time.monotonic() + timeout_ms / 1000.0
        sleep_s = 0.0002
        while True:
            with self._ring_lock:
                for ring in self._rings:
                    view = ring.try_read_view()
                    if view is not None:
                        return _ring_unpack(view)
            if time.monotonic() >= deadline:
                return None
            # exponential backoff to 2ms: a sleeping consumer leaves the cores
            # to the workers; sub-ms latency only matters on the first misses
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 2, 0.002)

    def ventilate(self, *args, **kwargs):
        self._ventilated_items += 1
        self._ventilator_send.send_pyobj((args, kwargs))

    def get_results(self, timeout_s=None):
        with obs.stage('pool_wait', cat='pool'):
            return self._get_results(timeout_s)

    def _get_results(self, timeout_s=None):
        timeout_s = timeout_s if timeout_s is not None else self._results_timeout_s
        deadline = (time.monotonic() + timeout_s) if timeout_s is not None else None
        while True:
            msg = self._poll_message(50)
            if msg is None:
                if self._all_done():
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError(
                        'No results from worker processes in {}s; {} items in flight'.format(
                            timeout_s, self._ventilated_items - self._completed_items))
                continue
            kind, seq, payload = msg
            if kind == _DATA:
                self.last_result_seq = seq
                return self._serializer.deserialize(payload)
            elif kind == _BLOB:
                self.last_result_seq = seq
                return self._serializer.deserialize(_read_blob(bytes(payload).decode()))
            elif kind == _DONE:
                self._completed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                if seq is not None and self.done_callback is not None:
                    self.done_callback(seq)
            elif kind == _METRICS:
                self._absorb_telemetry(payload)
            elif kind == _ERROR:
                raise pickle.loads(payload)
            # late _STARTED messages are ignored

    def _absorb_telemetry(self, payload):
        """Record a worker's cumulative metrics snapshot and merge its trace
        events into this process's span ring."""
        try:
            rec = pickle.loads(bytes(payload))
        except Exception as e:  # noqa: BLE001 - malformed telemetry must never kill the read loop
            logger.debug('dropping malformed worker telemetry message: %s', e)
            return
        if not isinstance(rec, dict):
            return
        self._telemetry_by_pid[rec.get('pid')] = rec.get('metrics') or {}
        obs.absorb_trace_events(rec.get('events'))

    def telemetry_snapshots(self):
        """Latest cumulative metrics snapshot of every worker process (for
        :func:`petastorm_tpu.observability.merge_snapshots`)."""
        return list(self._telemetry_by_pid.values())

    def _all_done(self):
        if self._ventilated_items > self._completed_items:
            return False
        if self._ventilator is not None and not self._ventilator.completed():
            return False
        return True

    def stop(self):
        if self._stopped:
            return
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        # slow-joiner-safe: a worker that connects its SUB socket after this
        # publish would miss it, so join() rebroadcasts while draining
        self._control_send.send(_CONTROL_FINISHED)

    def join(self):
        if not self._stopped:
            raise RuntimeError('join() must be called after stop()')
        deadline = time.monotonic() + 10
        while any(p.is_alive() for p in self._processes) and time.monotonic() < deadline:
            self._control_send.send(_CONTROL_FINISHED)
            # drain results so workers blocked on a full transport can exit
            if self._transport == 'zmq':
                while self._results_receive.poll(0):
                    self._results_receive.recv_multipart()
            else:
                with self._ring_lock:
                    for ring in self._rings:
                        while ring.try_read() is not None:
                            pass
            time.sleep(0.05)
        for p in self._processes:
            if p.is_alive():
                logger.warning('Terminating unresponsive worker pid=%s', p.pid)
                p.terminate()
            p.join()
        self._processes = []
        for ring in self._rings:
            ring.close()
        self._rings = []
        for sock in (self._ventilator_send, self._results_receive, self._control_send):
            if sock is not None:
                sock.close()
        self._context.term()
        if self._ipc_dir:
            shutil.rmtree(self._ipc_dir, ignore_errors=True)
        if self._blob_dir:
            # sweep unconsumed blobs (already-consumed ones were unlinked on
            # read; live mappings keep their pages regardless)
            shutil.rmtree(self._blob_dir, ignore_errors=True)
            self._blob_dir = None

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md).
        ``results_queue_depth`` is 0 here: buffered results live in zmq/ring
        transport buffers this process cannot observe."""
        return {'workers_count': self._workers_count,
                'items_ventilated': self._ventilated_items,
                'items_completed': self._completed_items,
                'items_in_flight': self._ventilated_items - self._completed_items,
                'results_queue_depth': 0}

    @property
    def results_qsize(self):
        return 0  # unknown: lives in zmq buffers


# ---------------------------------------------------------------------------
# Worker process side
# ---------------------------------------------------------------------------

def _worker_bootstrap(worker_id, main_pid, setup_blob, vent_addr, result_addr, control_addr,
                      results_hwm, ring_name=None, blob_dir=None, blob_threshold=0,
                      workers_count=1):
    """Entry point of a spawned worker process. ``ring_name`` selects the shm
    results transport; None = zmq PUSH. ``blob_dir`` enables the large-payload
    /dev/shm sidechannel."""
    # The native image-decode thread budget is PER-PROCESS state — sibling
    # workers cannot see each other's grants — so each spawned worker gets an
    # equal share of the host's cores (unless the user pinned the env var
    # explicitly, which children inherit and honor).
    if 'PSTPU_IMG_THREADS' not in os.environ:
        os.environ['PSTPU_IMG_THREADS'] = str(
            max(1, (os.cpu_count() or 1) // max(1, workers_count)))

    worker_class, worker_setup_args, serializer = pickle.loads(setup_blob)

    # telemetry rides the worker setup args: configure THIS process's level
    # and ring to match the reader's before any instrumented code runs
    if isinstance(worker_setup_args, dict) and worker_setup_args.get('telemetry') is not None:
        obs.configure(worker_setup_args['telemetry'])

    _start_orphan_monitor(main_pid)

    context = zmq.Context()
    vent_recv = context.socket(zmq.PULL)
    vent_recv.connect(vent_addr)
    control_recv = context.socket(zmq.SUB)
    control_recv.setsockopt(zmq.SUBSCRIBE, b'')
    control_recv.connect(control_addr)

    finished = {'flag': False}

    def check_finished():
        """Also polled while blocked on a full ring, so shutdown never
        deadlocks against an unconsumed results transport."""
        if not finished['flag'] and control_recv.poll(0):
            if control_recv.recv() == _CONTROL_FINISHED:
                finished['flag'] = True
        return finished['flag']

    ring = None
    result_send = None
    if ring_name is not None:
        from petastorm_tpu.native.shm_ring import ShmRing
        ring = ShmRing.attach(ring_name)

        def send(kind, seq, payload=b''):
            ring.write2(_ring_header(kind, seq), payload, stop_check=check_finished)
    else:
        result_send = context.socket(zmq.PUSH)
        result_send.setsockopt(zmq.SNDHWM, results_hwm)
        result_send.connect(result_addr)

        def send(kind, seq, payload=b''):
            seq_bytes = b'' if seq is None else str(seq).encode()
            result_send.send_multipart([kind, seq_bytes, payload])

    current = {'seq': None}  # seq of the item being processed, for publish tagging

    def _blob_backpressure(incoming):
        """The byte analog of the ring's capacity bound: blobs are unlinked on
        read, so the shared directory's total size IS the pool's unconsumed
        backlog. Block (stop-aware) until the new blob fits the budget."""
        while True:
            try:
                backlog = 0
                for e in os.scandir(blob_dir):
                    try:
                        backlog += e.stat().st_size
                    except FileNotFoundError:
                        # consumer unlinked the blob mid-scan — the normal
                        # contended condition, not a shutdown; keep summing
                        continue
            except OSError:
                return  # dir swept (shutdown race): the write will fail loudly
            if backlog + incoming <= _BLOB_BUDGET_BYTES or backlog == 0:
                return
            if check_finished():
                return
            time.sleep(0.002)

    # persistent tmpfs exhaustion must not degrade into a warn+retry treadmill
    # on every message: give up on the sidechannel after a few consecutive
    # allocation failures (the in-band path keeps working regardless)
    blob_fail = {'consecutive': 0, 'disabled': False}
    _BLOB_DISABLE_AFTER = 3

    def _note_blob_failure(e):
        blob_fail['consecutive'] += 1
        if blob_fail['consecutive'] >= _BLOB_DISABLE_AFTER:
            blob_fail['disabled'] = True
            logger.warning('blob allocation failed %d times (%s); disabling the '
                           '/dev/shm sidechannel for this worker',
                           blob_fail['consecutive'], e)
        else:
            logger.warning('blob allocation failed (%s); payload falling back '
                           'in-band', e)

    def _try_blob_write(parts, total):
        """Write an already-split payload into a fresh /dev/shm blob and send
        its name. False = allocation failed (noted; caller falls back in-band).
        posix_fallocate first: tmpfs exhaustion surfaces as a catchable ENOSPC
        here, NOT as a SIGBUS when an mmap write faults an unbackable page
        (same stance as the ring's pre-faulting create)."""
        import mmap
        _blob_backpressure(total)
        try:
            fd, path = tempfile.mkstemp(prefix='b', dir=blob_dir)
        except OSError as e:  # unwritable/vanished dir: degrade, not die
            _note_blob_failure(e)
            return False
        try:
            try:
                os.posix_fallocate(fd, 0, total)
                mm = mmap.mmap(fd, total)
            except OSError as e:  # ENOSPC / ENOMEM under pressure
                os.close(fd)
                os.unlink(path)
                _note_blob_failure(e)
                return False
            buf = serializer.write_parts_into(parts, mm)
            buf.release()  # the mmap refuses to close with live views
            mm.close()
            os.close(fd)
        except BaseException:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        blob_fail['consecutive'] = 0
        send(_BLOB, current['seq'], path.encode())
        return True

    def publish(data):
        # The payload is classified/framed ONCE (serialize_parts); every
        # channel consumes the same parts list. Routing: sub-blob-threshold
        # blocks gather-write STRAIGHT into the shm ring — one copy per byte
        # into warm pages, no b''.join staging, ragged image columns as raw
        # cell buffers instead of a pickle of the pixels. Blocks at/above the
        # threshold ride the /dev/shm blob sidechannel: its consumer views
        # are COW-mmap lazy (no upfront read-out copy), which beats a ring
        # copy-out for multi-MB payloads. Everything else goes in-band.
        blob_live = (blob_dir is not None and not blob_fail['disabled'])
        parts = (serializer.serialize_parts(data)
                 if hasattr(serializer, 'serialize_parts') else None)
        if parts is not None:
            total = serializer.parts_size(parts)
            fits_ring = ring is not None and total + 17 <= ring.capacity  # 9B+8B framing
            if fits_ring and (not blob_live or total < blob_threshold):
                ring.writev([_ring_header(_DATA, current['seq'])] + parts,
                            stop_check=check_finished)
                return
            if blob_live and total >= blob_threshold and _try_blob_write(parts, total):
                return
            send(_DATA, current['seq'], serializer.join_parts(parts))
            return
        send(_DATA, current['seq'], serializer.serialize(data))

    def flush_telemetry():
        """Ship this process's cumulative metrics snapshot (and drained trace
        events) to the main process over the results channel. Sent after each
        completed item: row groups are coarse, so the extra ~1KB message is
        noise next to the payloads, and cumulative snapshots make delivery
        loss-tolerant (the latest one supersedes all prior)."""
        if not obs.counters_on():
            return
        try:
            rec = {'pid': os.getpid(), 'metrics': obs.snapshot()}
            if obs.spans_on():
                rec['events'] = obs.drain_trace_events()
            send(_METRICS, None, pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception as e:  # noqa: BLE001 - telemetry is best-effort: a shutdown
            # race here must not resend _DONE/_ERROR and corrupt item accounting
            logger.debug('telemetry flush failed: %s', e)

    worker = worker_class(worker_id, publish, worker_setup_args)
    send(_STARTED, None)

    poller = zmq.Poller()
    poller.register(vent_recv, zmq.POLLIN)
    poller.register(control_recv, zmq.POLLIN)

    try:
        while True:
            events = dict(poller.poll(100))
            if control_recv in events or finished['flag']:
                if finished['flag'] or control_recv.recv() == _CONTROL_FINISHED:
                    break
            if vent_recv in events:
                args, kwargs = vent_recv.recv_pyobj()
                current['seq'] = kwargs.pop('_seq', None)
                try:
                    worker.process(*args, **kwargs)
                    send(_DONE, current['seq'])
                    flush_telemetry()
                except Exception:  # noqa: BLE001 - forwarded to the main process
                    exc = sys.exc_info()[1]
                    logger.exception('Worker %d failed', worker_id)
                    try:
                        blob = pickle.dumps(exc)
                    except Exception:  # unpicklable exception: forward a summary
                        blob = pickle.dumps(RuntimeError('{}: {}'.format(type(exc).__name__, exc)))
                    send(_ERROR, None, blob)
                    # seq-less sentinel: the failed item stays undelivered so a
                    # checkpoint re-reads it (see thread_pool.py)
                    send(_DONE, None)
                    flush_telemetry()
    finally:
        worker.shutdown()
        if ring is not None:
            ring.close()
        for sock in (vent_recv, result_send, control_recv):
            if sock is not None:
                sock.close()
        context.term()


def _start_orphan_monitor(main_pid):
    """Kill this worker when the main process disappears
    (reference process_pool.py:324-331)."""

    def monitor():
        while True:
            try:
                os.kill(main_pid, 0)
            except OSError:
                logger.warning('Main process %d is gone; worker exiting', main_pid)
                os._exit(1)
            time.sleep(1.0)

    threading.Thread(target=monitor, daemon=True).start()
